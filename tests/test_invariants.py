"""checkers/invariants/ — the vectorized consistency-model family.

Completeness is pinned by seeded anomaly corpora (ISSUE 10 acceptance):
every injected anomaly class (balance violation, write-skew pair,
long-fork split, session-guarantee break) must be detected by its
checker, clean control histories must verify valid, and the device
path's verdict must equal the host oracle twin's on every corpus entry.
Plus: the fault-window ddmin, the sim nemeses, campaign plan
validation, and the models-matrix flywheel smoke.
"""

import json
import os
import random

import numpy as np
import pytest

from jepsen_tpu.checkers.invariants import bank as inv_bank
from jepsen_tpu.checkers.invariants import packed as inv_packed
from jepsen_tpu.checkers.invariants import predicate as inv_pred
from jepsen_tpu.checkers.invariants import session as inv_sess
from jepsen_tpu.history.ops import INVOKE, OK, History, Op

SEEDS = [0, 1, 2]


# ---------------------------------------------------------------------------
# corpus builders: valid histories + surgical injectors
# ---------------------------------------------------------------------------

def bank_history(n_ops=60, n_accounts=4, balance=10, seed=0) -> History:
    """Serial bank history: transfers conserve, reads snapshot."""
    rng = random.Random(seed)
    accounts = {i: balance for i in range(n_accounts)}
    ops = []
    for i in range(n_ops):
        p = rng.randrange(3)
        if rng.random() < 0.5:
            ops.append(Op(type=INVOKE, process=p, f="read", value=None))
            ops.append(Op(type=OK, process=p, f="read",
                          value=dict(accounts)))
        else:
            frm, to = rng.sample(range(n_accounts), 2)
            amt = 1 + rng.randrange(4)
            v = {"from": frm, "to": to, "amount": amt}
            ops.append(Op(type=INVOKE, process=p, f="transfer", value=v))
            if accounts[frm] >= amt:
                accounts[frm] -= amt
                accounts[to] += amt
                ops.append(Op(type=OK, process=p, f="transfer", value=v))
            else:
                ops.append(Op(type="fail", process=p, f="transfer",
                              value=v, error="insufficient"))
    return History(ops)


def inject_bank_wrong_total(h: History, seed=0) -> History:
    rng = random.Random(seed)
    reads = [op for op in h.ops if op.type == OK and op.f == "read"]
    op = reads[rng.randrange(len(reads))]
    a = sorted(op.value)[0]
    op.value[a] += 3  # breaks conservation, stays non-negative
    return h

def inject_bank_negative(h: History, seed=0) -> History:
    rng = random.Random(seed)
    reads = [op for op in h.ops if op.type == OK and op.f == "read"]
    op = reads[rng.randrange(len(reads))]
    a, b = sorted(op.value)[:2]
    shift = op.value[a] + 5
    op.value[a] -= shift  # negative, but the TOTAL is conserved
    op.value[b] += shift
    return h


def lf_history(groups=3, group_size=3, n_reads=12, seed=0) -> History:
    """Serial long-fork history: each key written once, group reads
    observe the committed prefix."""
    rng = random.Random(seed)
    ops = []
    written = {}
    keys = list(range(groups * group_size))
    to_write = list(keys)
    rng.shuffle(to_write)
    p = 0

    def group_read():
        g = rng.randrange(groups)
        ks = range(g * group_size, (g + 1) * group_size)
        mops = [["r", k, written.get(k)] for k in ks]
        inv = [["r", k, None] for k in ks]
        return inv, mops

    reads_done = 0
    while to_write or reads_done < n_reads:
        p = (p + 1) % 4
        if to_write and (reads_done >= n_reads or rng.random() < 0.5):
            k = to_write.pop()
            ops.append(Op(type=INVOKE, process=p, f="txn",
                          value=[["w", k, k]]))
            ops.append(Op(type=OK, process=p, f="txn",
                          value=[["w", k, k]]))
            written[k] = k
        else:
            inv, mops = group_read()
            ops.append(Op(type=INVOKE, process=p, f="txn", value=inv))
            ops.append(Op(type=OK, process=p, f="txn", value=mops))
            reads_done += 1
    return History(ops)


def inject_long_fork(h: History) -> History:
    """Split two reads of one group: read A forgets k2, read B forgets
    k1 — the two now order the writes oppositely."""
    reads = [op for op in h.ops
             if op.type == OK and op.f == "txn"
             and all(m[0] == "r" for m in (op.value or []))]
    for ia in range(len(reads)):
        for ib in range(ia + 1, len(reads)):
            a, b = reads[ia], reads[ib]
            ka = {m[1] for m in a.value}
            if ka != {m[1] for m in b.value}:
                continue
            obs_a = {m[1] for m in a.value if m[2] is not None}
            obs_b = {m[1] for m in b.value if m[2] is not None}
            both = sorted(obs_a & obs_b)
            if len(both) < 2:
                continue
            k1, k2 = both[:2]
            for m in a.value:
                if m[1] == k2:
                    m[2] = None
            for m in b.value:
                if m[1] == k1:
                    m[2] = None
            return h
    raise AssertionError("corpus has no injectable read pair")


def ws_history(pairs=2, n_txns=20, seed=0) -> History:
    """Serial write-skew-workload history (valid): read the pair,
    write one key."""
    rng = random.Random(seed)
    kv = {}
    ops = []
    val = 0
    for i in range(n_txns):
        p = rng.randrange(3)
        g = rng.randrange(pairs)
        k1, k2 = 2 * g, 2 * g + 1
        inv = [["r", k1, None], ["r", k2, None]]
        mops = [["r", k1, kv.get(k1)], ["r", k2, kv.get(k2)]]
        if rng.random() < 0.8:
            w = rng.choice((k1, k2))
            inv.append(["w", w, val])
            mops.append(["w", w, val])
            kv[w] = val
            val += 1
        ops.append(Op(type=INVOKE, process=p, f="txn", value=inv))
        ops.append(Op(type=OK, process=p, f="txn", value=mops))
    return History(ops)


def inject_write_skew(h: History) -> History:
    """Rewrite two updating txns of one pair into the classic skew:
    both read the same pre-state, each writes a different key."""
    upd = [op for op in h.ops if op.type == OK and op.f == "txn"
           and any(m[0] == "w" for m in op.value)]
    for ia in range(len(upd)):
        for ib in range(ia + 1, len(upd)):
            a, b = upd[ia], upd[ib]
            ga = {m[1] // 2 for m in a.value}
            gb = {m[1] // 2 for m in b.value}
            if len(ga) == 1 and ga == gb:
                g = next(iter(ga))
                k1, k2 = 2 * g, 2 * g + 1
                # pre-state: what the FIRST txn read
                pre = {m[1]: m[2] for m in a.value if m[0] == "r"}
                wa = next(m for m in a.value if m[0] == "w")
                wb = next(m for m in b.value if m[0] == "w")
                if wa[1] == wb[1]:
                    wb[1] = k2 if wa[1] == k1 else k1
                # both read the identical pre-state (so each misses
                # the other's write), write different keys
                for m in b.value:
                    if m[0] == "r":
                        m[2] = pre[m[1]]
                # later reads must not re-anchor b's write after a's:
                # drop b's written value from any later read
                for op in h.ops:
                    if op is a or op is b or op.type != OK \
                            or op.f != "txn":
                        continue
                    for m in op.value:
                        if m[0] == "r" and m[1] == wb[1] \
                                and m[2] == wb[2]:
                            m[2] = pre.get(m[1])
                return h
    raise AssertionError("corpus has no injectable txn pair")


def sess_history(n_keys=3, n_txns=30, seed=0, pin_keys=False) -> History:
    """Serial session history: rmw chains + reads (valid).

    ``pin_keys=True`` gives every process its own key (single-key
    sessions — the shape the vectorized pass owns; multi-key WRITER
    sessions register cross-key obligations and route to the exact DAG
    walker)."""
    rng = random.Random(seed)
    kv = {}
    ops = []
    val = 0
    for i in range(n_txns):
        p = rng.randrange(3)
        k = p % n_keys if pin_keys else rng.randrange(n_keys)
        if rng.random() < 0.6:
            mops = [["r", k, kv.get(k)], ["w", k, val]]
            inv = [["r", k, None], ["w", k, val]]
            kv[k] = val
            val += 1
        else:
            mops = [["r", k, kv.get(k)]]
            inv = [["r", k, None]]
        ops.append(Op(type=INVOKE, process=p, f="txn", value=inv))
        ops.append(Op(type=OK, process=p, f="txn", value=mops))
    return History(ops)


def inject_session_break(h: History) -> History:
    """Make one process's LATER read of a key observe an EARLIER
    version it had already read past (monotonic-reads break)."""
    per_proc = {}
    for op in h.ops:
        if op.type == OK and op.f == "txn":
            for m in op.value:
                if m[0] == "r" and m[2] is not None:
                    per_proc.setdefault((op.process, m[1]),
                                        []).append((op, m))
    for (p, k), evs in sorted(per_proc.items(), key=repr):
        if len(evs) >= 2:
            prior_val = evs[-2][1][2]
            last_op, last_m = evs[-1]
            # rewind the session's LAST read to the initial state —
            # strictly earlier than the prior read's version — inside
            # a pure-read txn (so no other chain is disturbed)
            if prior_val is not None and len(last_op.value) == 1:
                last_m[2] = None
                return h
    raise AssertionError("corpus has no injectable session pair")


# ---------------------------------------------------------------------------
# completeness: every injected class detected; clean controls valid;
# device verdict == host oracle twin, verdict-for-verdict
# ---------------------------------------------------------------------------

def _pin_device_host(check_fn, h, **kw):
    dev = check_fn(h, use_device=True, **kw)
    host = check_fn(h, use_device=False, **kw)
    assert dev["valid?"] == host["valid?"], (dev, host)
    assert dev["anomaly-types"] == host["anomaly-types"], (dev, host)
    return dev


@pytest.mark.parametrize("seed", SEEDS)
def test_bank_clean_and_injected(seed):
    t = {"total-amount": 40}
    clean = bank_history(seed=seed)
    dev = _pin_device_host(
        lambda h, use_device, **kw: inv_bank.check(
            h, t, use_device=use_device), clean)
    assert dev["valid?"] is True

    bad = inject_bank_wrong_total(bank_history(seed=seed), seed)
    dev = _pin_device_host(
        lambda h, use_device, **kw: inv_bank.check(
            h, t, use_device=use_device), bad)
    assert dev["valid?"] is False
    assert "bank-wrong-total" in dev["anomaly-types"]
    assert dev["bad-reads"][0]["expected-total"] == 40

    neg = inject_bank_negative(bank_history(seed=seed), seed)
    dev = _pin_device_host(
        lambda h, use_device, **kw: inv_bank.check(
            h, t, use_device=use_device), neg)
    assert dev["valid?"] is False
    assert dev["anomaly-types"] == ["bank-negative-balance"]
    # the negative-balances-ok workload variant accepts it
    ok = inv_bank.check(neg, t, negative_balances_ok=True)
    assert ok["valid?"] is True


@pytest.mark.parametrize("seed", SEEDS)
def test_long_fork_clean_and_injected(seed):
    clean = lf_history(seed=seed)
    dev = _pin_device_host(
        lambda h, use_device, **kw: inv_pred.check(
            h, use_device=use_device), clean)
    assert dev["valid?"] is True, dev

    forked = inject_long_fork(lf_history(seed=seed))
    dev = _pin_device_host(
        lambda h, use_device, **kw: inv_pred.check(
            h, use_device=use_device), forked)
    assert dev["valid?"] is False
    assert "long-fork" in dev["anomaly-types"]
    wit = dev["anomalies"]["long-fork"][0]
    assert len(wit["reads"]) == 2 and len(wit["keys"]) == 2
    assert "why" in wit


@pytest.mark.parametrize("seed", SEEDS)
def test_write_skew_clean_and_injected(seed):
    clean = ws_history(seed=seed)
    dev = _pin_device_host(
        lambda h, use_device, **kw: inv_pred.check(
            h, use_device=use_device), clean)
    assert dev["valid?"] is True, dev

    skewed = inject_write_skew(ws_history(seed=seed))
    dev = _pin_device_host(
        lambda h, use_device, **kw: inv_pred.check(
            h, use_device=use_device), skewed)
    assert dev["valid?"] is False
    assert "write-skew" in dev["anomaly-types"], dev
    # the graph confirmation reports the G2 cycle with edge evidence
    cyc_names = [n for n in dev["anomaly-types"]
                 if n in ("G2-item", "G-nonadjacent", "G-single")]
    assert cyc_names, dev
    cyc = dev["anomalies"][cyc_names[0]][0]["cycle"]
    assert any("why" in e for e in cyc)


@pytest.mark.parametrize("seed", SEEDS)
def test_session_clean_and_injected(seed):
    clean = sess_history(seed=seed, pin_keys=True)
    dev = _pin_device_host(
        lambda h, use_device, **kw: inv_sess.check(
            h, use_device=use_device), clean)
    assert dev["valid?"] is True, dev
    assert not dev.get("fallback")  # single-key rmw chains vectorize

    broken = inject_session_break(sess_history(seed=seed,
                                               pin_keys=True))
    dev = _pin_device_host(
        lambda h, use_device, **kw: inv_sess.check(
            h, use_device=use_device), broken)
    assert dev["valid?"] is False
    assert not dev.get("fallback")
    assert "monotonic-reads-violation" in dev["anomaly-types"], dev


def test_session_agrees_with_dag_walker():
    """On single-key-session histories the vectorized pass and the
    exact DAG walker must agree on the anomaly set."""
    from jepsen_tpu.checkers.elle import sessions as walker

    for seed in SEEDS:
        broken = inject_session_break(sess_history(seed=seed,
                                                   pin_keys=True))
        vec = inv_sess.check(broken, use_device=False)
        assert not vec.get("fallback")
        ref = walker.check(broken)
        assert vec["valid?"] == ref["valid?"]
        assert vec["anomaly-types"] == ref["anomaly-types"]


def test_session_cross_key_sessions_vectorized():
    """Multi-key WRITER sessions register cross-key obligations; since
    ISSUE 12 the vectorized obligation pass covers them — NO walker
    fallback, and the verdict + anomaly set must equal the walker's."""
    from jepsen_tpu.checkers.elle import sessions as walker

    for seed in SEEDS:
        broken = inject_session_break(sess_history(seed=seed))
        res = inv_sess.check(broken)
        assert not res.get("fallback"), res.get("fallback")
        ref = walker.check(broken)
        assert res["valid?"] == ref["valid?"]
        assert res["anomaly-types"] == ref["anomaly-types"]


def test_session_cross_key_obligation_only_violation():
    """A violation visible ONLY through cross-key propagation (the
    observer's k1 reads are same-key-consistent): S1 reads k1@2 then
    writes k2; the observer reads that k2 version and afterwards an
    ANCESTOR of k1@2.  Walker and vectorized pass must both flag it."""
    from jepsen_tpu.checkers.elle import sessions as walker

    ops = []

    def txn(p, filled):
        ops.append(Op(type=INVOKE, process=p, f="txn",
                      value=[[m[0], m[1],
                              None if m[0] == "r" else m[2]]
                             for m in filled]))
        ops.append(Op(type=OK, process=p, f="txn", value=filled))

    txn(0, [["r", 1, None], ["w", 1, 1]])
    txn(0, [["r", 1, 1], ["w", 1, 2]])
    txn(0, [["r", 1, 2], ["w", 2, 10]])   # k2 write depends on k1@2
    txn(2, [["r", 2, 10]])                # observer activates
    txn(2, [["r", 1, 1]])                 # older than k1@2 -> WFR
    h = History(ops)
    res = inv_sess.check(h, use_device=False)
    ref = walker.check(h)
    assert not res.get("fallback")
    assert res["valid?"] is False
    assert "writes-follow-reads-violation" in res["anomaly-types"]
    assert res["valid?"] == ref["valid?"]
    assert res["anomaly-types"] == ref["anomaly-types"]


def test_session_branched_falls_back_to_walker():
    ops = []

    def txn(p, filled):
        ops.append(Op(type=INVOKE, process=p, f="txn",
                      value=[[m[0], m[1],
                              None if m[0] == "r" else m[2]]
                             for m in filled]))
        ops.append(Op(type=OK, process=p, f="txn", value=filled))

    txn(0, [["r", 0, None], ["w", 0, 1]])
    txn(0, [["w", 0, 2]])  # blind write: init branches
    res = inv_sess.check(History(ops))
    assert res.get("fallback") == "dag-walker"


def test_long_fork_vectorized_matches_pairwise_oracle():
    """The bucketed matrix pass against the quadratic reference scan,
    over seeded corpora (clean + injected)."""
    for seed in SEEDS:
        for h in (lf_history(seed=seed),
                  inject_long_fork(lf_history(seed=seed))):
            vec, n_reads, _ = inv_pred.long_forks(
                inv_packed.pack_rw(h), use_device=False)
            ref = inv_pred.oracle_long_forks(h)
            assert bool(vec) == bool(ref), (vec, ref)
            assert n_reads > 0
            # every vectorized fork names a key pair the oracle also
            # implicates (witness choice may differ)
            ref_keys = {frozenset(f["keys"]) for f in ref}
            for f in vec:
                assert frozenset(f["keys"]) in ref_keys


# ---------------------------------------------------------------------------
# resilience: guarded device seam + deadline contract
# ---------------------------------------------------------------------------

def test_bank_device_fault_degrades_to_host():
    from jepsen_tpu.resilience import FaultPlan, RetryPolicy

    t = {"total-amount": 40}
    bad = inject_bank_wrong_total(bank_history(seed=1), 1)
    plan = FaultPlan(seed=3, persistent=("invariants.bank",),
                     kinds=("oom",))
    res = inv_bank.check(bad, t, plan=plan,
                         policy=RetryPolicy(max_attempts=2,
                                            base_delay_s=0.0, seed=0))
    assert res["valid?"] is False
    assert res.get("degraded") == "host-fallback"
    assert "bank-wrong-total" in res["anomaly-types"]


def test_predicate_deadline_returns_attributable_unknown():
    from jepsen_tpu.resilience import Deadline

    h = inject_long_fork(lf_history(seed=0))
    res = inv_pred.check(h, deadline=Deadline(0.0))
    assert res["valid?"] == "unknown"
    assert "deadline" in str(res.get("error"))


# ---------------------------------------------------------------------------
# packed core
# ---------------------------------------------------------------------------

def test_pack_bank_shapes():
    h = bank_history(n_ops=30, seed=2)
    pb = inv_packed.pack_bank(h)
    assert pb.balances.shape == (pb.n_reads, pb.n_accounts)
    assert pb.n_reads > 0 and pb.n_accounts == 4
    # committed reads only; every row sums to the conserved total
    assert (pb.balances.sum(axis=1) == 40).all()
    assert len(pb.tr_type) > 0


def test_infer_rw_chain_ranks():
    h = sess_history(seed=0)
    p = inv_packed.pack_rw(h)
    inf = inv_packed.infer_rw(p)
    assert inf.chain_ok.all()
    # ranks: init is 0, written versions positive, per key contiguous
    V = p.n_vals
    assert (inf.chain_rank[V:] == 0).all()
    assert (inf.chain_rank[:V] > 0).all()


# ---------------------------------------------------------------------------
# fault-window ddmin
# ---------------------------------------------------------------------------

def _nem(f, idx):
    return [Op(type=INVOKE, process="nemesis", f=f, value=None),
            Op(type="info", process="nemesis", f=f, value=None)]


def _windowed_bank_history():
    """Three skew windows; the bad read sits inside the SECOND."""
    ops = []

    def read(p, v):
        ops.append(Op(type=INVOKE, process=p, f="read", value=None))
        ops.append(Op(type=OK, process=p, f="read", value=dict(v)))

    good = {0: 10, 1: 10}
    ops += _nem("start-skew", 0) + _nem("stop-skew", 0)   # window 1
    read(0, good)
    ops += _nem("start-skew", 0)                          # window 2
    read(1, {0: 10, 1: 7})                                # bad read
    ops += _nem("stop-skew", 0)
    read(0, good)
    ops += _nem("start-skew", 0) + _nem("stop-skew", 0)   # window 3
    read(2, good)
    return History(ops)


def test_fault_window_ddmin_keeps_overlapping_window(tmp_path):
    from jepsen_tpu import minimize
    from jepsen_tpu.workloads.bank import BankChecker

    h = _windowed_bank_history()
    test = {"name": "win", "store-dir": str(tmp_path / "s"),
            "history": h, "checker": BankChecker(),
            "total-amount": 20, "workload-kind": "bank"}
    s1 = minimize.shrink(dict(test), workers=1, force=True)
    assert s1["valid?"] is False
    wins = s1["fault-windows"]
    assert len(wins) == 1, wins  # only the overlapping window survives
    assert wins[0]["f"] == "start-skew"
    nem_ops = [op for op in s1["witness-history"]
               if op.process == "nemesis"]
    assert len(nem_ops) == 4  # start pair + stop pair
    # digest-stable at any worker count, windows included
    s3 = minimize.shrink(dict(test), workers=3, force=True)
    assert s3["digest"] == s1["digest"]
    assert s3["fault-windows"] == wins


def test_fault_windows_grouping():
    from jepsen_tpu.minimize import reduce as reduce_mod

    h = _windowed_bank_history()
    units = reduce_mod.units_of(h)
    nem = [u for u in units if reduce_mod.is_nemesis_unit(u)]
    wins = reduce_mod.fault_windows(nem)
    assert len(wins) == 3
    desc = reduce_mod.window_descriptors(nem, wins)
    assert all(d["f"] == "start-skew" for d in desc)
    assert all(d["span"][0] < d["span"][1] for d in desc)


def test_one_shot_faults_are_own_windows():
    from jepsen_tpu.minimize import reduce as reduce_mod

    ops = (_nem("leave-node", 0) + _nem("join-node", 0)
           + _nem("start-skew", 0) + _nem("bump-clock", 0)
           + _nem("stop-skew", 0) + _nem("leave-node", 0))
    units = reduce_mod.units_of(History(ops))
    wins = reduce_mod.fault_windows(units)
    # leave, join, [start..bump..stop], leave
    assert [len(w) for w in wins] == [1, 1, 3, 1]


def _xhost_history():
    """Two hosts' instances of the same schedule position, only host
    A's covering the torn read (the ISSUE 11 cross-host shape) — the
    shared fixture `synth.cross_host_window_history` (scripts/
    fuzz_faults.py pins the same shape)."""
    from jepsen_tpu.workloads import synth

    return synth.cross_host_window_history("hostA", "hostB")


def test_fault_windows_group_by_host():
    """ISSUE 11: window-stamped nemesis units group by (host, digest)
    — each host's instance of a schedule position is its own window —
    and the descriptors carry the schedule identity + host
    attribution."""
    from jepsen_tpu.minimize import reduce as reduce_mod

    units = reduce_mod.units_of(_xhost_history())
    nem = [u for u in units if reduce_mod.is_nemesis_unit(u)]
    wins = reduce_mod.fault_windows(nem)
    assert len(wins) == 2
    desc = reduce_mod.window_descriptors(nem, wins,
                                         ["overlap", "necessary"])
    assert [(d["host"], d["digest"], d["kept"]) for d in desc] == \
        [("hostB", "win-hostB", "overlap"),
         ("hostA", "win-hostA", "necessary")]
    # stamped and unstamped units coexist: an unscheduled one-shot
    # fault still groups heuristically beside the stamped windows
    extra = reduce_mod.units_of(History(
        list(_xhost_history()) + _nem("bump-clock", 0)))
    nem2 = [u for u in extra if reduce_mod.is_nemesis_unit(u)]
    assert len(reduce_mod.fault_windows(nem2)) == 3


def test_cross_host_ddmin_attributes_necessary_window(tmp_path):
    """The cross-host fault-window ddmin end to end: a fault-sensitive
    checker that needs host A's window keeps exactly that window,
    marked reproduction-necessary and host-attributed; host B's
    (disjoint, droppable) window goes — digest-stable at any probe
    worker count."""
    from jepsen_tpu import minimize
    from jepsen_tpu.checkers.api import FnChecker
    from jepsen_tpu.workloads import synth

    host_sensitive = synth.cross_host_sensitive_check("hostA")
    test = {"name": "xhost", "store-dir": str(tmp_path / "s"),
            "history": _xhost_history()}
    s1 = minimize.shrink(dict(test),
                         checker=FnChecker(host_sensitive, "x-host"),
                         workers=1, force=True)
    assert s1["valid?"] is False
    assert [(w["host"], w["kept"], w["digest"])
            for w in s1["fault-windows"]] == \
        [("hostA", "necessary", "win-hostA")]
    s3 = minimize.shrink(dict(test),
                         checker=FnChecker(host_sensitive, "x-host"),
                         workers=3, force=True)
    assert s3["digest"] == s1["digest"]
    assert s3["fault-windows"] == s1["fault-windows"]


def test_interleaved_package_windows_pair_by_family():
    """Composed packages interleave: stop-skew must close start-skew,
    not the partition window opened in between."""
    from jepsen_tpu.minimize import reduce as reduce_mod

    ops = (_nem("start-skew", 0) + _nem("start-partition", 0)
           + _nem("stop-skew", 0) + _nem("stop-partition", 0))
    units = reduce_mod.units_of(History(ops))
    wins = reduce_mod.fault_windows(units)
    desc = reduce_mod.window_descriptors(units, wins)
    fams = sorted((d["f"], len(w)) for d, w in zip(desc, wins))
    assert fams == [("start-partition", 2), ("start-skew", 2)]
    # and a bare stop with no family match still closes the most
    # recent open window rather than orphaning
    ops = _nem("start-skew", 0) + _nem("fast", 0)
    units = reduce_mod.units_of(History(ops))
    assert [len(w) for w in reduce_mod.fault_windows(units)] == [2]


# ---------------------------------------------------------------------------
# sim nemeses
# ---------------------------------------------------------------------------

def test_sim_skew_nemesis_tears_bank_reads():
    from jepsen_tpu.nemesis.sim import SimClockSkewNemesis
    from jepsen_tpu.workloads.mem import MemClient, MemStore

    s = MemStore()
    s.accounts = {0: 10, 1: 10}
    c = MemClient(s).open({"nodes": ["n1"]}, "n1")
    t = {"client": c, "workload-kind": "bank", "nodes": ["n1"]}
    nem = SimClockSkewNemesis(random.Random(0))
    comp = nem.invoke(t, {"f": "start-skew", "value": None,
                          "type": "invoke"})
    assert comp["type"] == "info"
    assert "faketime" in comp["value"]  # FAKETIME-spec'd offset
    # move money, then read under skew: some reads tear
    for i in range(6):
        c.invoke(t, {"f": "transfer",
                     "value": {"from": 0, "to": 1, "amount": 2}})
    sums = {sum(c.invoke(t, {"f": "read", "value": None})["value"]
                .values()) for _ in range(16)}
    assert any(x != 20 for x in sums), sums
    nem.invoke(t, {"f": "stop-skew", "value": None, "type": "invoke"})
    assert sum(c.invoke(t, {"f": "read", "value": None})["value"]
               .values()) == 20


def test_sim_membership_removed_node_fails_cleanly():
    from jepsen_tpu.nemesis.membership import MembershipNemesis
    from jepsen_tpu.nemesis.sim import SimMembershipState
    from jepsen_tpu.workloads.mem import MemClient, MemStore

    s = MemStore()
    nodes = ["n1", "n2"]
    c1 = MemClient(s).open({}, "n1")
    c2 = MemClient(s).open({}, "n2")
    t = {"client": c1, "nodes": nodes}
    nem = MembershipNemesis(SimMembershipState(nodes),
                            converge_timeout_s=2.0,
                            poll_interval_s=0.01).setup(t)
    comp = nem.invoke(t, {"f": "leave-node", "value": "n2",
                          "type": "invoke"})
    assert comp["type"] == "ok" and comp["value"]["converged"]
    r = c2.invoke(t, {"f": "txn", "value": [["r", 0, None]]})
    assert r["type"] == "fail" and r["error"] == "node-removed"
    assert c1.invoke(t, {"f": "txn",
                         "value": [["r", 0, None]]})["type"] == "ok"
    # rejoin heals
    comp = nem.invoke(t, {"f": "join-node", "value": "n2",
                          "type": "invoke"})
    assert comp["type"] == "ok"
    assert c2.invoke(t, {"f": "txn",
                         "value": [["r", 0, None]]})["type"] == "ok"


# ---------------------------------------------------------------------------
# campaign plan validation (the bare-resolution-error fix)
# ---------------------------------------------------------------------------

def test_expand_names_unknown_workload():
    from jepsen_tpu.campaign import plan as plan_mod

    with pytest.raises(ValueError) as ei:
        plan_mod.expand({"name": "x", "workloads": ["bankk"],
                         "seeds": [0]})
    msg = str(ei.value)
    assert "bankk" in msg
    assert "registered workloads" in msg
    assert "bank" in msg and "noop" in msg  # the list is actually there


def test_cli_campaign_rejects_unknown_workload(tmp_path, capsys):
    """The CLI surfaces plan-time validation as a clean exit-2 error
    naming the workload — not a mid-fleet traceback."""
    from jepsen_tpu import cli

    p = tmp_path / "bad.json"
    p.write_text(json.dumps({"name": "bad", "workloads": ["bankk"],
                             "seeds": [0]}))
    rc = cli.run(cli.single_test_cmd(lambda o: o),
                 ["--store-dir", str(tmp_path), "campaign", "run",
                  str(p)])
    assert rc == 2
    err = capsys.readouterr().err
    assert "bankk" in err and "registered workloads" in err


def test_registered_workloads_pass_validation():
    from jepsen_tpu.campaign import plan as plan_mod

    plan_mod.register_workload("inv-test-wl", lambda o: {})
    try:
        specs = plan_mod.expand({"name": "x",
                                 "workloads": ["inv-test-wl"],
                                 "seeds": [0]})
        assert len(specs) == 1
    finally:
        plan_mod._EXTRA_WORKLOADS.pop("inv-test-wl", None)


def test_new_workloads_classified_device():
    from jepsen_tpu.campaign import plan as plan_mod

    specs = plan_mod.expand({"name": "x",
                             "workloads": ["bank", "write-skew",
                                           "session", "long-fork"],
                             "seeds": [0]})
    assert all(rs.device for rs in specs)


# ---------------------------------------------------------------------------
# the flywheel, end to end: models-matrix campaign -> invalid cell ->
# auto-shrink -> fault-window-minimized witness -> web witness page
# ---------------------------------------------------------------------------

SPEC_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "specs", "models-matrix.json")


@pytest.fixture(scope="module")
def models_matrix_store(tmp_path_factory):
    from jepsen_tpu import cli

    base = str(tmp_path_factory.mktemp("models"))
    rc = cli.run(cli.single_test_cmd(lambda o: o),
                 ["--store-dir", base, "campaign", "run", SPEC_PATH,
                  "--workers", "2"])
    return base, rc


def test_models_matrix_campaign_smoke(models_matrix_store):
    from jepsen_tpu.campaign import core as ccore
    from jepsen_tpu.campaign import plan as plan_mod
    from jepsen_tpu.campaign.index import Index

    base, rc = models_matrix_store
    assert rc == 1  # invalid cells exist, and that's the exit contract
    spec = plan_mod.load_spec(SPEC_PATH)
    idx = Index(ccore.index_path(spec["name"], base))
    specs = plan_mod.expand(spec)
    assert idx.completed_ids() == {rs.run_id for rs in specs}
    by_label = {}
    for rec in idx.records:
        by_label.setdefault(rec["workload"], []).append(rec)
        assert rec["valid?"] in (True, False, "unknown")
    # the bank-under-skew cells produce real invalid histories with
    # auto-shrunk witnesses whose fault windows are recorded
    bank_skew = [r for r in by_label.get("bank-skew", ())
                 if r["valid?"] is False]
    assert bank_skew, by_label.get("bank-skew")
    wit = bank_skew[0].get("witness")
    assert wit and wit.get("ops"), wit
    assert "bank-wrong-total" in (wit.get("anomaly-types") or ())


def test_models_matrix_witness_page_and_windows(models_matrix_store):
    import urllib.request

    from jepsen_tpu import web
    from jepsen_tpu.campaign import core as ccore
    from jepsen_tpu.campaign import plan as plan_mod
    from jepsen_tpu.campaign.index import Index
    from jepsen_tpu.minimize import load_witness

    base, _ = models_matrix_store
    spec = plan_mod.load_spec(SPEC_PATH)
    idx = Index(ccore.index_path(spec["name"], base))
    rec = next(r for r in idx.records
               if r["workload"] == "bank-skew" and r["valid?"] is False
               and (r.get("witness") or {}).get("ops"))
    d = os.path.join(base, rec["dir"])
    w = load_witness(d)
    assert w is not None
    assert w.get("fault-windows") is not None  # meta records the set
    srv = web.serve(port=0, base=base, background=True)
    try:
        port = srv.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/run/{rec['dir']}/witness",
                timeout=10) as resp:
            body = resp.read().decode()
        assert "minimal witness" in body
        assert "expected" in body  # the bank bad-read rendering
        if w.get("fault-windows"):
            assert "surviving fault windows" in body
    finally:
        srv.shutdown()
        srv.server_close()


def test_models_matrix_gate_applies_to_checker_spans(
        models_matrix_store, tmp_path):
    """`cli obs gate` evaluates the new checker spans: with only one
    generation it must exit 2 (cannot evaluate) with a reason — the
    applicability contract — and after a second generation it
    evaluates to a real verdict (0 or 1, never a crash)."""
    from jepsen_tpu import cli
    from jepsen_tpu.campaign import core as ccore

    base, _ = models_matrix_store
    rc = cli.run(cli.single_test_cmd(lambda o: o),
                 ["--store-dir", base, "obs", "ingest"])
    assert rc == 0
    rc = cli.run(cli.single_test_cmd(lambda o: o),
                 ["--store-dir", base, "obs", "gate",
                  "--campaign", "models-matrix",
                  "--span", "check:bank", "--min-runs", "2"])
    assert rc == 2  # one generation: cannot evaluate, never silent
    # second generation (shrink off: the spans under test are the
    # checkers'), then the gate has a real before/after to compare
    spec = json.load(open(SPEC_PATH))
    spec["opts"].pop("shrink", None)
    p2 = tmp_path / "gen2.json"
    p2.write_text(json.dumps(spec))
    rc = cli.run(cli.single_test_cmd(lambda o: o),
                 ["--store-dir", base, "campaign", "run", str(p2),
                  "--workers", "2", "--rerun"])
    assert rc in (0, 1)
    rc = cli.run(cli.single_test_cmd(lambda o: o),
                 ["--store-dir", base, "obs", "gate",
                  "--campaign", "models-matrix",
                  "--span", "check:bank", "--min-runs", "2"])
    assert rc in (0, 1)
