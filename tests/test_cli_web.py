"""CLI + web UI tests (reference cli/web layer, SURVEY.md §2.1 L7/§3.5)."""

import json
import os
import time
import urllib.request
import zipfile

import pytest

from jepsen_tpu import cli, core, store, web
from jepsen_tpu.checkers.api import Stats
from jepsen_tpu.generator import core as g
from jepsen_tpu.workloads.mem import MemClient


# ---------------------------------------------------------------- cli bits

def test_parse_concurrency():
    assert cli.parse_concurrency("30", 5) == 30
    assert cli.parse_concurrency("10n", 5) == 50
    assert cli.parse_concurrency("3n", 0) == 3
    with pytest.raises(ValueError):
        cli.parse_concurrency("x2", 3)


def test_parse_nodes(tmp_path):
    f = tmp_path / "nodes.txt"
    f.write_text("n4\nn5\n")
    assert cli.parse_nodes(["n1,n2", "n3"], str(f)) == \
        ["n1", "n2", "n3", "n4", "n5"]
    assert cli.parse_nodes(None, None) == []


def _test_fn(opts):
    return {
        **opts,
        "name": "cli-test",
        "nodes": opts.get("nodes") or ["n1"],
        "concurrency": 2,
        "client": MemClient(),
        "generator": g.clients(g.limit(
            6, lambda t, c: {"f": "read", "value": None})),
        "checker": Stats(),
    }


def test_cli_run_test(tmp_path, capsys):
    rc = cli.run(cli.single_test_cmd(_test_fn),
                 ["--store-dir", str(tmp_path / "s"),
                  "test", "--time-limit", "10", "--test-count", "2"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "run 1/2" in out and "run 2/2" in out
    assert "valid? = True" in out
    assert len(store.tests("cli-test", base=str(tmp_path / "s"))) == 2


def test_cli_analyze(tmp_path, capsys):
    rc = cli.run(cli.single_test_cmd(_test_fn, checker_fn=Stats),
                 ["--store-dir", str(tmp_path / "s"),
                  "test", "--time-limit", "5"])
    assert rc == 0
    d = store.latest("cli-test", base=str(tmp_path / "s"))
    rc = cli.run(cli.single_test_cmd(_test_fn, checker_fn=Stats),
                 ["analyze", d])
    assert rc == 0
    assert "valid? = True" in capsys.readouterr().out


def test_cli_test_all(tmp_path, capsys):
    fns = {"a": _test_fn, "b": _test_fn}
    rc = cli.run(cli.test_all_cmd(fns),
                 ["--store-dir", str(tmp_path / "s"),
                  "test-all", "--time-limit", "5"])
    assert rc == 0
    assert capsys.readouterr().out.count("valid? = True") == 2


def test_cli_demo_suite(tmp_path, capsys):
    from jepsen_tpu.__main__ import DEMOS
    rc = cli.run(cli.test_all_cmd(DEMOS),
                 ["--store-dir", str(tmp_path / "s"),
                  "test-all", "--only", "bank", "--time-limit", "2"])
    assert rc == 0
    assert "demo-bank" in capsys.readouterr().out


# ---------------------------------------------------------------- web

@pytest.fixture
def served_store(tmp_path):
    base = str(tmp_path / "s")
    t = core.run(_test_fn({"store-dir": base}))
    srv = web.serve(port=0, base=base, background=True)
    port = srv.server_address[1]
    yield base, port, t
    srv.shutdown()
    srv.server_close()


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
        return r.status, r.headers.get("Content-Type", ""), r.read()


def test_web_index_and_files(served_store):
    base, port, t = served_store
    status, ctype, body = _get(port, "/")
    assert status == 200 and b"cli-test" in body
    # run dir listing
    rel = os.path.relpath(store.test_dir(t), base)
    status, _, body = _get(port, f"/files/{rel}/")
    assert status == 200 and b"results.json" in body
    # file fetch
    status, ctype, body = _get(port, f"/files/{rel}/results.json")
    assert status == 200 and json.loads(body)["valid?"] is True


def test_web_zip_download(served_store, tmp_path):
    base, port, t = served_store
    rel = os.path.relpath(store.test_dir(t), base)
    status, ctype, body = _get(port, f"/zip/{rel}")
    assert status == 200 and ctype == "application/zip"
    zp = tmp_path / "run.zip"
    zp.write_bytes(body)
    names = zipfile.ZipFile(zp).namelist()
    assert any(n.endswith("results.json") for n in names)


def test_web_traversal_blocked(served_store):
    base, port, _ = served_store
    import urllib.error
    # encoded traversal out of the store dir must 404
    try:
        status, _, _ = _get(port, "/files/..%2f..%2fetc%2fpasswd")
    except urllib.error.HTTPError as e:
        status = e.code
    assert status == 404


# -- review regressions ----------------------------------------------------

def test_cli_extra_opts_reach_test_fn(tmp_path):
    seen = {}

    def fn(opts):
        seen.update(opts)
        return _test_fn(opts)

    rc = cli.run(cli.single_test_cmd(
        fn, extra_opts=lambda p: p.add_argument("--rate", type=int)),
        ["--store-dir", str(tmp_path / "s"), "test", "--rate", "7",
         "--time-limit", "5"])
    assert rc == 0
    assert seen.get("rate") == 7


def test_cli_analyze_without_checker_clean_error(tmp_path, capsys):
    cli.run(cli.single_test_cmd(_test_fn),
            ["--store-dir", str(tmp_path / "s"), "test", "--time-limit", "5"])
    d = store.latest("cli-test", base=str(tmp_path / "s"))
    rc = cli.run(cli.single_test_cmd(_test_fn), ["analyze", d])
    assert rc == 2
    assert "checker" in capsys.readouterr().err


def test_cli_test_all_unknown_name(capsys):
    rc = cli.run(cli.test_all_cmd({"a": _test_fn}),
                 ["test-all", "--only", "bogus"])
    assert rc == 2
    assert "bogus" in capsys.readouterr().err


def test_json_log_formatter_escapes():
    import logging
    rec = logging.LogRecord("x", logging.INFO, "f", 1,
                            'he said "boom"\nline2', (), None)
    out = cli._JsonFormatter().format(rec)
    assert json.loads(out)["msg"] == 'he said "boom"\nline2'


def test_drain_survives_transient_fails():
    from jepsen_tpu.workloads.queue import _Drain, _is_empty_fail
    assert not _is_empty_fail({"type": "fail", "f": "dequeue",
                               "error": "simulated-abort"})
    assert _is_empty_fail({"type": "fail", "f": "dequeue", "error": "empty"})
    d = _Drain()
    d2 = d.update({}, None, {"type": "fail", "f": "dequeue",
                             "error": "timeout"})
    assert not d2.done
    d3 = d2.update({}, None, {"type": "fail", "f": "dequeue",
                              "error": "empty"})
    assert d3.done


def test_cli_shrink_smoke(tmp_path, capsys):
    """`cli shrink <dir>` (ISSUE 4): shrink a stored invalid run to a
    minimal witness, then serve its /run/<rel>/witness page."""
    from jepsen_tpu.checkers.elle import oracle
    from jepsen_tpu.workloads import synth

    base = str(tmp_path / "s")
    h = synth.la_history(n_txns=60, n_keys=5, concurrency=4, seed=7)
    assert synth.inject_wr_cycle(h)
    t = core.noop_test(name="shrink-smoke")
    t["store-dir"] = base
    t["history"] = h
    store.save_0(t)
    t["results"] = oracle.check(h, ["serializable"])
    store.save_1(t)
    d = store.test_dir(t)

    rc = cli.run(cli.single_test_cmd(_test_fn),
                 ["shrink", d, "--host-oracle", "--anomaly", "G1c"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "witness:" in out and "G1c" in out
    assert os.path.exists(os.path.join(d, "witness.json"))
    assert os.path.exists(os.path.join(d, "witness.jsonl"))
    # cached second run reports [cached]
    rc = cli.run(cli.single_test_cmd(_test_fn),
                 ["shrink", d, "--host-oracle", "--anomaly", "G1c"])
    assert rc == 0
    assert "[cached]" in capsys.readouterr().out

    srv = web.serve(port=0, base=base, background=True)
    try:
        port = srv.server_address[1]
        rel = os.path.relpath(d, base)
        status, _, body = _get(port, f"/run/{rel}/witness")
        assert status == 200
        assert b"minimal witness" in body and b"G1c" in body
        # the run page links to it
        status, _, body = _get(port, f"/run/{rel}")
        assert status == 200 and b"/witness" in body
        # a run without a witness 404s cleanly
        import urllib.error
        try:
            status, _, _ = _get(port, "/run/nope/witness")
        except urllib.error.HTTPError as e:
            status = e.code
        assert status == 404
    finally:
        srv.shutdown()
        srv.server_close()


def test_web_telemetry_percentile_table(tmp_path):
    """The per-run telemetry page renders p50/p95/p99 computed from
    the fixed-bucket histograms (ROADMAP telemetry open item) instead
    of raw bucket dumps."""
    import json as _json

    from jepsen_tpu import telemetry

    base = str(tmp_path / "s")
    coll = telemetry.activate()
    coll.registry.histogram("demo-latency-s",
                            buckets=(0.01, 0.1, 1.0)).observe(0.05)
    for v in (0.02, 0.03, 0.5, 2.0):
        coll.registry.histogram("demo-latency-s",
                                buckets=(0.01, 0.1, 1.0)).observe(v)
    t = core.run(_test_fn({"store-dir": base}))
    d = store.test_dir(t)
    telemetry.deactivate(coll)
    telemetry.write_run(d, coll)
    status_doc = _json.load(open(os.path.join(d, "telemetry.json")))
    assert status_doc["metrics"]["histograms"]

    srv = web.serve(port=0, base=base, background=True)
    try:
        port = srv.server_address[1]
        rel = os.path.relpath(d, base)
        status, _, body = _get(port, f"/telemetry/{rel}")
        assert status == 200
        assert b"latency percentiles" in body
        assert b"demo-latency-s" in body
        assert b"p50" in body and b"p99" in body
    finally:
        srv.shutdown()
        srv.server_close()


def test_cli_tail_smoke(tmp_path, capsys):
    """`cli tail <run-dir>` (ISSUE 5): renders the streamed
    events.jsonl with the open-span / final-counter footer."""
    base = str(tmp_path / "s")
    t = core.run(_test_fn({"store-dir": base, "telemetry": True}))
    d = store.test_dir(t)
    assert os.path.exists(os.path.join(d, "events.jsonl"))
    rc = cli.run(cli.single_test_cmd(_test_fn), ["tail", d])
    assert rc == 0
    out = capsys.readouterr().out
    assert "run ended cleanly" in out
    assert "workload" in out and "interpreter-ops" in out
    # -n limits the event lines
    rc = cli.run(cli.single_test_cmd(_test_fn), ["tail", d, "-n", "2"])
    assert rc == 0
    assert "earlier events" in capsys.readouterr().out
    # -n 0 is footer-only, not everything (lst[-0:] is the whole list)
    rc = cli.run(cli.single_test_cmd(_test_fn), ["tail", d, "-n", "0"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "run ended cleanly" in out and "open  " not in out
    # an unstreamed run dir gets a clean error, not a stack trace
    t2 = core.run(_test_fn({"store-dir": str(tmp_path / "s2")}))
    rc = cli.run(cli.single_test_cmd(_test_fn),
                 ["tail", store.test_dir(t2)])
    assert rc == 2
    assert "events.jsonl" in capsys.readouterr().err


def test_cli_tail_follow_exits_on_end_mid_batch(tmp_path, capsys):
    """`tail -f` must exit when "end" is not the poll batch's LAST
    event — a sampler tick racing the recorder's close can append one
    straggler line after it."""
    import threading

    from jepsen_tpu.telemetry import stream as tel_stream

    d = str(tmp_path / "r")
    os.makedirs(d)
    s = tel_stream.EventStream(os.path.join(d, "events.jsonl"))
    s.emit("span-open", name="run", tid=1)
    s.emit("end", valid=True)
    s.emit("sample", gauges={"process-rss-bytes": 1})  # straggler
    rc = {}
    th = threading.Thread(
        target=lambda: rc.setdefault("rc", cli.run(
            cli.single_test_cmd(_test_fn), ["tail", d, "-f"])),
        daemon=True)
    th.start()
    th.join(timeout=15)
    assert not th.is_alive(), "tail -f never saw the mid-batch end"
    assert rc["rc"] == 0


def test_web_live_run_page(tmp_path):
    """/live/<rel> (ISSUE 5): the auto-refreshing in-flight view —
    ended runs render statically, missing streams 404."""
    import urllib.error

    base = str(tmp_path / "s")
    t = core.run(_test_fn({"store-dir": base, "telemetry": True}))
    srv = web.serve(port=0, base=base, background=True)
    try:
        port = srv.server_address[1]
        rel = os.path.relpath(store.test_dir(t), base)
        status, _, body = _get(port, f"/live/{rel}")
        assert status == 200
        assert b"ended" in body and b"event tail" in body
        assert b"http-equiv" not in body  # finished: no auto-refresh
        # the index and run pages link to it
        status, _, body = _get(port, "/")
        assert status == 200 and b"/live/" in body
        status, _, body = _get(port, f"/run/{rel}")
        assert status == 200 and b"/live/" in body
        # an in-flight (still-open) stream auto-refreshes and names
        # the open span chain
        d2 = os.path.join(base, "cli-test", "20990101T000000.000Z")
        os.makedirs(d2)
        from jepsen_tpu.telemetry import stream as tel_stream

        s = tel_stream.EventStream(os.path.join(d2, "events.jsonl"))
        s.emit("span-open", name="run", tid=1)
        s.emit("span-open", name="check:wedged", tid=1)
        rel2 = os.path.relpath(d2, base)
        status, _, body = _get(port, f"/live/{rel2}")
        assert status == 200
        assert b"http-equiv" in body  # refreshing
        assert b"check:wedged" in body and b"in flight" in body
        # a long-quiet stream (crashed run that never emits "end")
        # stops auto-refreshing but keeps the open-span post-mortem
        old = time.time() - 3600
        os.utime(os.path.join(d2, "events.jsonl"), (old, old))
        status, _, body = _get(port, f"/live/{rel2}")
        assert status == 200
        assert b"http-equiv" not in body
        assert b"stream idle" in body and b"check:wedged" in body
        try:
            status, _, _ = _get(port, "/live/nope")
        except urllib.error.HTTPError as e:
            status = e.code
        assert status == 404
    finally:
        srv.shutdown()
        srv.server_close()


def test_web_campaign_live_and_witness_diff(tmp_path):
    """/campaign/<name>/live + /campaign/<name>/witness-diff (ISSUE 5):
    the fleet heartbeat dashboard and the cross-generation witness
    comparison."""
    import urllib.error

    from jepsen_tpu import telemetry
    from jepsen_tpu.campaign.core import live_path
    from jepsen_tpu.campaign.index import Index

    base = str(tmp_path / "s")
    os.makedirs(os.path.join(base, "campaigns"))
    hb = telemetry.Heartbeat(live_path("demo", base), campaign="demo",
                             total=4, done=1, min_interval_s=0.0)
    hb.worker("campaign-worker-0", {"run": "run-abc", "workload":
                                    "append", "fault": "nofault",
                                    "seed": 3, "slot": 0})
    idx = Index(os.path.join(base, "campaigns", "demo.jsonl"))
    for gen, ops, dig in (("g1", 6, "aaa"), ("g2", 4, "bbb")):
        idx.append({"run": "r1", "key": "append|f|0", "valid?": False,
                    "gen": gen, "witness": {"ops": ops, "digest": dig,
                                            "anomaly-types": ["G1c"]}})
    srv = web.serve(port=0, base=base, background=True)
    try:
        port = srv.server_address[1]
        status, _, body = _get(port, "/campaign/demo/live")
        assert status == 200
        assert b"run-abc" in body and b"1/4" in body
        assert b"http-equiv" in body  # not finished: refreshing
        # a killed scheduler never writes finished=True: once the
        # heartbeat goes stale the dashboard stops auto-refreshing
        hb.state["updated"] = time.time() - 3600
        doc = json.dumps(hb.state)
        with open(live_path("demo", base), "w") as f:
            f.write(doc)
        status, _, body = _get(port, "/campaign/demo/live")
        assert status == 200
        assert b"http-equiv" not in body and b"stalled?" in body
        status, _, body = _get(port, "/campaign/demo/witness-diff")
        assert status == 200
        assert b"append|f|0" in body
        assert b"6 &rarr; 4" in body and b"changed" in body
        # the campaign page links to both
        status, _, body = _get(port, "/campaign/demo")
        assert status == 200
        assert b"/campaign/demo/live" in body
        assert b"/campaign/demo/witness-diff" in body
        try:
            status, _, _ = _get(port, "/campaign/nope/live")
        except urllib.error.HTTPError as e:
            status = e.code
        assert status == 404
    finally:
        srv.shutdown()
        srv.server_close()


def test_cli_demo_causal(tmp_path, capsys):
    from jepsen_tpu.__main__ import DEMOS
    rc = cli.run(cli.test_all_cmd(DEMOS),
                 ["--store-dir", str(tmp_path / "s"),
                  "test-all", "--only", "causal", "--time-limit", "2",
                  "--ops", "4000"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "demo-causal" in out and "valid? = True" in out


def test_cli_cpu_flag_forces_cpu_backend(tmp_path, monkeypatch):
    """--cpu (or JT_FORCE_CPU) must drop the TPU/axon backend factories
    before the checkers' first jax init — on a box whose tunnel is down,
    backend init hangs rather than raising, so this is the only exit.
    The spy pins that the flag actually CALLS the force (the conftest
    already CPU-forces this process, so the backend alone proves
    nothing); JT_FORCE_CPU=0/false/no must NOT trigger it."""
    from jepsen_tpu import cli
    from jepsen_tpu.__main__ import DEMOS
    from jepsen_tpu.utils import backend as backend_mod

    calls = []
    real = backend_mod.force_cpu_backend
    monkeypatch.setattr(backend_mod, "force_cpu_backend",
                        lambda *a, **k: (calls.append(1), real(*a, **k)))
    rc = cli.run(cli.test_all_cmd(DEMOS, prog="demo"),
                 ["--store-dir", str(tmp_path), "--cpu",
                  "test-all", "--only", "set", "--time-limit", "1"])
    assert rc == 0
    assert calls, "--cpu did not invoke force_cpu_backend"
    import jax

    assert jax.default_backend() == "cpu"

    # falsy env spellings must not silently downgrade a TPU box
    calls.clear()
    monkeypatch.setenv("JT_FORCE_CPU", "0")
    rc = cli.run(cli.test_all_cmd(DEMOS, prog="demo"),
                 ["--store-dir", str(tmp_path / "b"),
                  "test-all", "--only", "set", "--time-limit", "1"])
    assert rc == 0
    assert not calls, "JT_FORCE_CPU=0 must not force the CPU backend"
    # and a truthy spelling does
    calls.clear()
    monkeypatch.setenv("JT_FORCE_CPU", "1")
    rc = cli.run(cli.test_all_cmd(DEMOS, prog="demo"),
                 ["--store-dir", str(tmp_path / "c"),
                  "test-all", "--only", "set", "--time-limit", "1"])
    assert rc == 0
    assert calls, "JT_FORCE_CPU=1 must force the CPU backend"


# ------------------------------------------- ISSUE 6: the observatory

def test_parse_since():
    now = 1_000_000_000.0
    assert cli.parse_since("90s", now) == now - 90
    assert cli.parse_since("5m", now) == now - 300
    assert cli.parse_since("2h", now) == now - 7200
    assert cli.parse_since("1d", now) == now - 86400
    assert cli.parse_since("45", now) == now - 45  # bare small: duration
    assert cli.parse_since("1722650000", now) == 1722650000.0  # epoch
    assert cli.parse_since("1970-01-01T00:01:40", now) == 100.0
    with pytest.raises(ValueError):
        cli.parse_since("next tuesday", now)


def test_cli_tail_since_scan_and_warehouse_agree(tmp_path, capsys):
    """`tail --since` filters to recent events — from the stream scan
    when no warehouse covers the run, from the indexed event table
    when one does; both views must render identically."""
    base = str(tmp_path / "s")
    t = core.run(_test_fn({"store-dir": base, "telemetry": True}))
    d = store.test_dir(t)
    disp = cli.single_test_cmd(_test_fn)
    assert cli.run(disp, ["tail", d, "--since", "1h"]) == 0
    scan_out = capsys.readouterr().out
    assert "run ended cleanly" in scan_out
    # --since now: every event is older, nothing renders but the
    # truncated-stream footer
    assert cli.run(disp, ["tail", d, "--since", "0s"]) == 0
    out = capsys.readouterr().out
    assert "no open spans" in out and " span " not in out
    # bad spec: clean error
    assert cli.run(disp, ["tail", d, "--since", "nope"]) == 2
    capsys.readouterr()
    # now build the warehouse: same question, indexed answer
    from jepsen_tpu.telemetry import warehouse as wmod

    wh = wmod.open_or_create(base)
    wh.ingest_store(base)
    assert wh.events_fresh(d, base)
    assert cli.run(disp, ["tail", d, "--since", "1h"]) == 0
    assert capsys.readouterr().out == scan_out


def test_web_metrics_endpoint(tmp_path):
    """/metrics (ISSUE 6): Prometheus text exposition with the
    pinned content type; campaign heartbeats and warehouse rollups
    appear when present."""
    base = str(tmp_path / "s")
    os.makedirs(os.path.join(base, "campaigns"))
    with open(os.path.join(base, "campaigns", "soak.jsonl"), "w") as f:
        f.write(json.dumps({"campaign": "soak", "run": "r1",
                            "key": "k", "valid?": True, "gen": "g1",
                            "spans": {"check:la": 1.0}}) + "\n")
    with open(os.path.join(base, "campaigns",
                           "soak.live.json"), "w") as f:
        json.dump({"campaign": "soak", "updated": time.time(),
                   "total": 4, "done": 1, "workers": {},
                   "finished": False}, f)
    from jepsen_tpu.telemetry import warehouse as wmod

    wmod.open_or_create(base).ingest_store(base)
    srv = web.serve(port=0, base=base, background=True)
    try:
        port = srv.server_address[1]
        status, ctype, body = _get(port, "/metrics")
        assert status == 200
        assert ctype.startswith("text/plain; version=0.0.4")
        text = body.decode()
        assert "# TYPE jepsen_campaign_runs_done gauge" in text
        assert 'jepsen_campaign_runs_done{campaign="soak"} 1' in text
        assert ('jepsen_warehouse_campaign_runs{campaign="soak",'
                'valid="true"} 1') in text
        assert text.endswith("\n")
        # the index page links to it
        status, _, body = _get(port, "/")
        assert b"/metrics" in body
    finally:
        srv.shutdown()
        srv.server_close()


def test_web_trend_page_and_run_page_warehouse_spans(tmp_path):
    """/campaign/<name>/trend (ISSUE 6): the per-generation span p95
    table the gate enforces; and the run page's warehouse-backed span
    profile."""
    base = str(tmp_path / "s")
    t = core.run(_test_fn({"store-dir": base, "telemetry": True}))
    rel = os.path.relpath(store.test_dir(t), base)
    os.makedirs(os.path.join(base, "campaigns"), exist_ok=True)
    with open(os.path.join(base, "campaigns", "soak.jsonl"), "w") as f:
        for gen, dur in (("g1", 1.0), ("g1", 1.1), ("g2", 2.0)):
            f.write(json.dumps({
                "campaign": "soak", "run": f"r-{gen}-{dur}", "key": "k",
                "valid?": True, "gen": gen,
                "spans": {"check:la": dur}}) + "\n")
        # check:aaa sorts FIRST and skips g2 (samples in g1 + g3 only):
        # column order must stay chronological (g1 g2 g3), not
        # per-span first-seen — which would yield g1 g3 g2 and
        # mis-pair every row's adjacent-column delta highlight
        for gen in ("g1", "g3"):
            f.write(json.dumps({
                "campaign": "soak", "run": f"r-{gen}-aaa", "key": "k2",
                "valid?": True, "gen": gen,
                # aaa doubles g1 -> g3, but with NO g2 sample between:
                # the highlight promises adjacent-generation deltas,
                # so the gap must suppress it (asserted below)
                "spans": {"check:aaa": 2.0 if gen == "g3" else 1.0,
                          **({"check:la": 2.1} if gen == "g3"
                             else {})}}) + "\n")
    from jepsen_tpu.telemetry import warehouse as wmod

    wmod.open_or_create(base).ingest_store(base)
    srv = web.serve(port=0, base=base, background=True)
    try:
        port = srv.server_address[1]
        status, _, body = _get(port, "/campaign/soak/trend")
        assert status == 200
        text = body.decode()
        assert "check:la" in text
        assert "<th>g1</th>" in text and "<th>g2</th>" in text
        # chronological columns even though check:aaa (sorted first)
        # has no g2 samples
        assert text.index("<th>g1</th>") < text.index("<th>g2</th>") \
            < text.index("<th>g3</th>")
        assert "obs gate" in text  # tells you how to enforce it
        # >25% step vs the previous generation is highlighted — and
        # ONLY for adjacent generations: check:la's g1->g2 step is the
        # single red cell; check:aaa's g1->g3 doubling straddles a
        # missing g2 and must not be compared across the gap
        assert text.count("background:#f2a3a3") == 1
        # the campaign page links to the trend page
        status, _, body = _get(port, "/campaign/soak")
        assert status == 200 and b"/campaign/soak/trend" in body
        # run page: span profile from the warehouse's run_spans table
        status, _, body = _get(port, f"/run/{rel}")
        assert status == 200
        assert b"warehouse" in body and b"check:Stats" in body
    finally:
        srv.shutdown()
        srv.server_close()
