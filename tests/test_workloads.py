"""Workload package tests: each workload runs end-to-end on the sim
cluster and its checker reaches the right verdict (SURVEY.md §2.6/§4)."""

import random

import pytest

from jepsen_tpu import core, independent
from jepsen_tpu.generator import core as g
from jepsen_tpu.history.ops import History, history, invoke, ok
from jepsen_tpu.workloads import (append, bank, linearizable_register,
                                  long_fork, queue, sets, wr)
from jepsen_tpu.workloads.mem import MemClient, MemStore, bank_store


def run_workload(tmp_path, wl, client, *, n_ops=30, concurrency=4, **kw):
    t = {
        "name": "wl-test",
        "nodes": ["n1", "n2"],
        "client": client,
        "concurrency": concurrency,
        "store-dir": str(tmp_path / "store"),
        **{k: v for k, v in wl.items()
           if k not in ("generator", "checker", "final-generator")},
        "generator": g.clients(g.limit(n_ops, wl["generator"])),
        "checker": wl["checker"],
        **kw,
    }
    if "final-generator" in wl:
        t["final-generator"] = wl["final-generator"]
    return core.run(t)


# ---------------------------------------------------------------- append

def test_append_workload_valid(tmp_path):
    wl = append.workload(rng=random.Random(1))
    done = run_workload(tmp_path, wl, MemClient())
    assert done["results"]["valid?"] is True


def test_append_gen_unique_appends():
    gen = append.gen(key_count=3, max_writes_per_key=4,
                     rng=random.Random(2))
    seen = set()
    for _ in range(200):
        op = gen({}, None)
        for kind, k, v in op["value"]:
            if kind == "append":
                assert (k, v) not in seen, "duplicate append"
                seen.add((k, v))


def test_append_key_rotation():
    gen = append.gen(key_count=2, max_writes_per_key=3, read_frac=0.0,
                     rng=random.Random(3))
    keys = set()
    for _ in range(100):
        for kind, k, v in gen({}, None)["value"]:
            keys.add(k)
    assert len(keys) > 2  # retired keys were replaced with fresh ones


# ---------------------------------------------------------------- wr

def test_wr_workload_valid(tmp_path):
    wl = wr.workload(rng=random.Random(4))
    done = run_workload(tmp_path, wl,
                        MemClient(txn_kind="rw-register"))
    assert done["results"]["valid?"] in (True, "unknown")


# ----------------------------------------------------- linearizable register

def test_linearizable_register_valid(tmp_path):
    wl = linearizable_register.workload(rng=random.Random(5))
    done = run_workload(tmp_path, wl, MemClient(), n_ops=20, concurrency=3)
    assert done["results"]["valid?"] is True


# ---------------------------------------------------------------- bank

def test_bank_workload_valid(tmp_path):
    wl = bank.workload(n_accounts=4, total=40, rng=random.Random(6))
    store = MemStore()
    store.accounts = dict(wl["accounts"])
    done = run_workload(tmp_path, wl, MemClient(store))
    assert done["results"]["valid?"] is True
    assert done["results"]["read-count"] > 0


def test_bank_checker_catches_bad_total():
    h = history([
        invoke(0, "read", None), ok(0, "read", {0: 10, 1: 10}),
        invoke(0, "read", None), ok(0, "read", {0: 10, 1: 5}),
    ])
    res = bank.BankChecker().check({"total-amount": 20}, h)
    assert res["valid?"] is False
    assert res["bad-read-count"] == 1


def test_bank_checker_catches_negative():
    h = history([
        invoke(0, "read", None), ok(0, "read", {0: 25, 1: -5}),
    ])
    res = bank.BankChecker().check({"total-amount": 20}, h)
    assert res["valid?"] is False
    res2 = bank.BankChecker(negative_balances_ok=True).check(
        {"total-amount": 20}, h)
    assert res2["valid?"] is True


# ---------------------------------------------------------------- long fork

def test_long_fork_valid(tmp_path):
    wl = long_fork.workload(rng=random.Random(7))
    done = run_workload(tmp_path, wl,
                        MemClient(txn_kind="rw-register"), n_ops=40)
    assert done["results"]["valid?"] in (True, "unknown")


def test_long_fork_detected():
    # reads order w(0) and w(1) oppositely
    h = history([
        invoke(0, "txn", [("w", 0, 0)]), ok(0, "txn", [("w", 0, 0)]),
        invoke(1, "txn", [("w", 1, 1)]), ok(1, "txn", [("w", 1, 1)]),
        invoke(2, "txn", [("r", 0, None), ("r", 1, None)]),
        ok(2, "txn", [("r", 0, 0), ("r", 1, None)]),
        invoke(3, "txn", [("r", 0, None), ("r", 1, None)]),
        ok(3, "txn", [("r", 0, None), ("r", 1, 1)]),
    ])
    res = long_fork.LongForkChecker().check({}, h)
    assert res["valid?"] is False
    assert res["fork-count"] >= 1


# ---------------------------------------------------------------- set

def test_set_workload_valid(tmp_path):
    wl = sets.workload(rng=random.Random(8))
    done = run_workload(tmp_path, wl, MemClient(), n_ops=20)
    assert done["results"]["valid?"] is True


def test_set_full_workload(tmp_path):
    wl = sets.workload(full=True, rng=random.Random(9))
    done = run_workload(tmp_path, wl, MemClient(), n_ops=30)
    assert done["results"]["valid?"] in (True, "unknown")


# ---------------------------------------------------------------- queue

def test_queue_workload_valid(tmp_path):
    wl = queue.workload(rng=random.Random(10))
    done = run_workload(tmp_path, wl, MemClient(), n_ops=30)
    assert done["results"]["valid?"] is True


# ---------------------------------------------------------------- independent

def test_independent_sequential(tmp_path):
    keys = ["a", "b"]
    gen = independent.sequential_generator(
        keys, lambda k: g.limit(4, lambda t, c: {"f": "read", "value": None}))
    # values get wrapped as (k, v) tuples
    done = core.run({
        "name": "indep", "client": MemClient(), "concurrency": 2,
        "nodes": ["n1"], "generator": g.clients(gen),
        "store-dir": str(tmp_path / "s"),
    })
    vals = [op.value for op in done["history"] if op.type == "invoke"]
    assert all(independent.is_tuple(v) for v in vals)
    assert {v[0] for v in vals} == {"a", "b"}


def test_independent_concurrent_groups(tmp_path):
    keys = [0, 1, 2, 3]
    gen = independent.concurrent_generator(
        2, keys, lambda k: g.limit(3, lambda t, c: {"f": "read", "value": None}))
    done = core.run({
        "name": "indep-c", "client": MemClient(), "concurrency": 4,
        "nodes": ["n1"], "generator": g.clients(gen),
        "store-dir": str(tmp_path / "s"),
    })
    invs = [op for op in done["history"] if op.type == "invoke"]
    assert len(invs) == 12  # 4 keys x 3 ops
    assert {op.value[0] for op in invs} == set(keys)
    # group 0 (threads 0-1) and group 1 (threads 2-3) touch disjoint keys
    for op in invs:
        group = 0 if op.process % 4 in (0, 1) else 1
        assert op.value[0] in (keys[:2] if group == 0 else keys[2:])


def test_independent_checker_splits_and_merges():
    from jepsen_tpu.checkers.api import Stats

    h = history([
        invoke(0, "read", ("k1", None)), ok(0, "read", ("k1", 1)),
        invoke(1, "read", ("k2", None)), ok(1, "read", ("k2", 2)),
    ])
    res = independent.checker(Stats).check({}, h)
    assert res["valid?"] is True
    assert res["key-count"] == 2


def test_independent_checker_reports_failing_key():
    from jepsen_tpu.checkers.api import Checker

    class _FailK2(Checker):
        def check(self, test, history, opts=None):
            bad = any(op.value == "poison" for op in history)
            return {"valid?": not bad}

    h = history([
        invoke(0, "w", ("k1", 1)), ok(0, "w", ("k1", 1)),
        invoke(1, "w", ("k2", "poison")), ok(1, "w", ("k2", "poison")),
    ])
    res = independent.checker(_FailK2).check({}, h)
    assert res["valid?"] is False
    assert res["failures"] == ["k2"]


# -- review regressions ----------------------------------------------------


def test_bank_workload_nondivisible_total(tmp_path):
    wl = bank.workload(n_accounts=3, total=10, rng=random.Random(11))
    assert wl["total-amount"] == sum(wl["accounts"].values())
    store = MemStore()
    store.accounts = dict(wl["accounts"])
    done = run_workload(tmp_path, wl, MemClient(store))
    assert done["results"]["valid?"] is True


def test_bank_checker_modal_total_inference():
    # 2 good reads, 1 skewed: majority sum wins, skewed read flagged
    h = history([
        invoke(0, "read", None), ok(0, "read", {0: 10, 1: 10}),
        invoke(0, "read", None), ok(0, "read", {0: 15, 1: 10}),
        invoke(0, "read", None), ok(0, "read", {0: 10, 1: 10}),
    ])
    res = bank.BankChecker().check({}, h)
    assert res["valid?"] is False
    assert res["bad-read-count"] == 1
    assert res["bad-reads"][0]["total"] == 25


def test_workloads_import_without_jax(monkeypatch):
    # host-only workloads must not drag jax in at import time
    import importlib, subprocess, sys
    code = (
        "import sys\n"
        "sys.modules['jax'] = None\n"  # poison: any import jax explodes
        "import jepsen_tpu.workloads.bank, jepsen_tpu.workloads.queue\n"
        "import jepsen_tpu.workloads.append\n"
        "print('ok')\n"
    )
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, cwd="/root/repo")
    assert "ok" in out.stdout, out.stderr


def test_independent_checker_copies_stateful_instances():
    from jepsen_tpu.checkers.api import Checker

    class Stateful(Checker):
        def __init__(self):
            self.seen = []

        def check(self, test, history, opts=None):
            self.seen.extend(op.value for op in history)
            return {"valid?": len(self.seen) <= 2}

    h = history([
        invoke(0, "w", ("k1", 1)), ok(0, "w", ("k1", 1)),
        invoke(1, "w", ("k2", 2)), ok(1, "w", ("k2", 2)),
    ])
    res = independent.checker(Stateful()).check({}, h)
    assert res["valid?"] is True  # no cross-key contamination


# ---- causal workload (jepsen/tests/causal.clj equivalent) ----------------

def test_causal_valid_history():
    from jepsen_tpu.history import history, invoke, ok
    from jepsen_tpu.workloads import causal

    # serial rmw chain + reads that respect causality
    h = history([
        invoke(0, "txn", [("r", "x", None), ("w", "x", 1)]),
        ok(0, "txn", [("r", "x", None), ("w", "x", 1)]),
        invoke(1, "txn", [("r", "x", None), ("w", "x", 2)]),
        ok(1, "txn", [("r", "x", 1), ("w", "x", 2)]),
        invoke(0, "txn", [("r", "x", None)]),
        ok(0, "txn", [("r", "x", 2)]),
    ])
    res = causal.CausalChecker().check({}, h)
    assert res["valid?"] is True, res


def test_causal_monotonic_read_violation_detected():
    from jepsen_tpu.history import history, invoke, ok
    from jepsen_tpu.workloads import causal

    # P1 installs v1 then v2 (rmw chain); P2 reads 2 then 1 — a
    # monotonic-reads (session/causal) violation
    h = history([
        invoke(0, "txn", [("w", "x", 1)]),
        ok(0, "txn", [("w", "x", 1)]),
        invoke(0, "txn", [("r", "x", None), ("w", "x", 2)]),
        ok(0, "txn", [("r", "x", 1), ("w", "x", 2)]),
        invoke(1, "txn", [("r", "x", None)]),
        ok(1, "txn", [("r", "x", 2)]),
        invoke(1, "txn", [("r", "x", None)]),
        ok(1, "txn", [("r", "x", 1)]),
    ])
    res = causal.CausalChecker().check({}, h)
    assert res["valid?"] is False, res
    assert any("G-single-process" in a or "G1c-process" in a
               or "G0-process" in a for a in res["anomaly-types"]), res


def test_causal_write_cycle_detected():
    from jepsen_tpu.history import history, invoke, ok
    from jepsen_tpu.workloads import causal

    # wr cycle across processes: each reads the other's write before
    # writing (G1c) — forbidden under causal
    h = history([
        invoke(0, "txn", [("w", "x", 1), ("r", "y", None)]),
        invoke(1, "txn", [("w", "y", 9), ("r", "x", None)]),
        ok(0, "txn", [("w", "x", 1), ("r", "y", 9)]),
        ok(1, "txn", [("w", "y", 9), ("r", "x", 1)]),
    ])
    res = causal.CausalChecker().check({}, h)
    assert res["valid?"] is False, res


def test_causal_generator_shape():
    import random

    from jepsen_tpu.workloads import causal

    g = causal.gen(rng=random.Random(1))
    ops = [g({}, None) for _ in range(20)]
    assert all(o["f"] == "txn" for o in ops)
    assert any(len(o["value"]) == 2 for o in ops)  # rmw txns present
