"""The watchtower (ISSUE 20): declarative SLO/alert engine.

Contracts under test:

- **journal durability**: ``alerts.jsonl`` is the AutopilotJournal
  discipline verbatim — fsync'd appends, torn-tail tolerance with
  heal-once, independent replay to the identical state digest;
- **state machine**: breach → pending, held ``for_s`` → firing,
  clean → resolved; ``for_s == 0`` fires in the same tick with both
  transitions journaled in order;
- **at-most-once notification**: the journaled intent is the commit
  point — a kill -9 between intent and send DROPS the delivery, a
  replayed engine re-fed the same breaching signals never re-sends;
- **signal collection**: registry gauges/counters (summed across
  label sets), heartbeat ages, store byte watermarks, autopilot gate
  state, warehouse rollups — each source best-effort;
- **twin-pass parole** (satellite): a quarantined key whose witness
  re-checks INVALID through its host twin is never paroled, however
  many clean generations pass; twin-valid (device false positive)
  paroles; the parole journal event stays replay-stable.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap

import pytest

from jepsen_tpu.telemetry import alerts as alerts_mod
from jepsen_tpu.telemetry.alerts import (
    AlertEngine,
    AlertJournal,
    Rule,
    alerts_path,
    collect_signals,
    load_rules,
    stock_rules,
)


def _engine(base, rules, **kw):
    return AlertEngine(str(base), rules=load_rules(rules), **kw)


class _Capture:
    """A sink that records every payload it is handed."""

    def __init__(self, fail=False):
        self.sent = []
        self.fail = fail

    def send(self, payload):
        if self.fail:
            raise ConnectionError("sink down")
        self.sent.append(payload)


# ------------------------------------------------------ rule parsing

def test_rule_roundtrip_and_aliases():
    r = Rule("x", kind="rate", severity="page", signal="gauge:g",
             op=">=", value=2.5, for_s=7.0, window_s=30.0)
    assert Rule.from_dict(r.to_dict()).to_dict() == r.to_dict()
    # Prometheus-style spellings parse to the canonical fields
    alias = Rule.from_dict({"name": "y", "for": 9.0, "window": 45.0})
    assert alias.for_s == 9.0 and alias.window_s == 45.0


def test_rule_validation_rejects_unknowns():
    with pytest.raises(ValueError):
        Rule("x", kind="nope")
    with pytest.raises(ValueError):
        Rule("x", severity="whatever")
    with pytest.raises(ValueError):
        Rule("x", op="!=")


def test_stock_pack_covers_the_known_smells():
    names = {r.name for r in stock_rules()}
    assert {"campaign-heartbeat-stale", "fleet-claim-latency-p95-high",
            "fleet-workers-alive-low", "quarantine-storm",
            "autopilot-gate-regression", "autopilot-gate-rc2-streak",
            "fleet-journal-bytes-growth", "worker-rss-watermark",
            "compile-cache-fallthrough-rate"} == names


def test_store_config_overrides_pack_and_declares_sinks(tmp_path):
    with open(tmp_path / "alerts.json", "w") as f:
        json.dump({"rules": [{"name": "only", "signal": "gauge:x",
                              "value": 1.0}],
                   "sinks": [{"file": "notes.jsonl"}]}, f)
    eng = AlertEngine(str(tmp_path))
    assert [r.name for r in eng.rules] == ["only"]
    assert len(eng.sinks) == 1
    # relative file sink lands inside the store
    eng.evaluate(signals={"gauge:x": 5.0}, now=10.0)
    assert os.path.exists(tmp_path / "notes.jsonl")


def test_shipped_example_pack_matches_stock(tmp_path):
    spec = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "specs", "alert-rules.json")
    with open(spec) as f:
        doc = json.load(f)
    assert {r.name for r in load_rules(doc)} == \
        {r.name for r in stock_rules()}


# ---------------------------------------------------- state machine

def test_threshold_debounce_pending_then_firing(tmp_path):
    cap = _Capture()
    eng = _engine(tmp_path, [{"name": "hot", "signal": "gauge:t",
                              "op": ">", "value": 10.0, "for": 5.0}],
                  sinks=[cap])
    eng.evaluate(signals={"gauge:t": 11.0}, now=100.0)
    assert eng.journal.states["hot"]["state"] == "pending"
    assert not cap.sent  # pending never notifies
    eng.evaluate(signals={"gauge:t": 12.0}, now=103.0)
    assert eng.journal.states["hot"]["state"] == "pending"
    eng.evaluate(signals={"gauge:t": 12.0}, now=105.0)
    st = eng.journal.states["hot"]
    assert st["state"] == "firing" and st["since"] == 105.0
    assert [p["state"] for p in cap.sent] == ["firing"]
    # resolve notifies exactly once, from firing only
    eng.evaluate(signals={"gauge:t": 1.0}, now=106.0)
    assert eng.journal.states["hot"]["state"] == "resolved"
    assert [p["state"] for p in cap.sent] == ["firing", "resolved"]


def test_for_zero_fires_same_tick_both_events_journaled(tmp_path):
    eng = _engine(tmp_path, [{"name": "now", "signal": "gauge:t",
                              "op": ">", "value": 0.0}], sinks=[])
    eng.evaluate(signals={"gauge:t": 1.0}, now=50.0)
    assert eng.journal.states["now"]["state"] == "firing"
    kinds = []
    with open(alerts_path(str(tmp_path)), "rb") as f:
        for line in f:
            ev = json.loads(line)
            if ev.get("ev") == "state":
                kinds.append(ev["state"])
    assert kinds == ["pending", "firing"]


def test_pending_resolves_quietly(tmp_path):
    cap = _Capture()
    eng = _engine(tmp_path, [{"name": "blip", "signal": "gauge:t",
                              "op": ">", "value": 0.0, "for": 60.0}],
                  sinks=[cap])
    eng.evaluate(signals={"gauge:t": 1.0}, now=10.0)
    eng.evaluate(signals={"gauge:t": 0.0}, now=11.0)
    assert eng.journal.states["blip"]["state"] == "resolved"
    assert not cap.sent  # a blip that never fired never notifies


def test_absence_and_freshness_kinds(tmp_path):
    eng = _engine(tmp_path, [
        {"name": "gone", "kind": "absence", "signal": "gauge:must"},
        {"name": "stale", "kind": "freshness",
         "signal": "heartbeat:max-age-s", "value": 300.0}], sinks=[])
    # absence breaches on a missing signal; freshness stays QUIET on
    # one (an idle store must not page) and breaches only past the age
    eng.evaluate(signals={}, now=10.0)
    assert eng.journal.states["gone"]["state"] == "firing"
    assert "stale" not in eng.journal.states
    eng.evaluate(signals={"gauge:must": 1.0,
                          "heartbeat:max-age-s": 301.0}, now=20.0)
    assert eng.journal.states["gone"]["state"] == "resolved"
    assert eng.journal.states["stale"]["state"] == "firing"


def test_rate_rule_needs_covered_window_then_breaches(tmp_path):
    eng = _engine(tmp_path, [{"name": "surge", "kind": "rate",
                              "signal": "counter:c", "op": ">",
                              "value": 1.0, "window": 10.0}],
                  sinks=[])
    # growth of 50/10s = 5/s, but the window is not yet covered:
    # a fresh engine must not alert off two early samples
    eng.evaluate(signals={"counter:c": 0.0}, now=100.0)
    eng.evaluate(signals={"counter:c": 50.0}, now=102.0)
    assert "surge" not in eng.journal.states
    eng.evaluate(signals={"counter:c": 150.0}, now=111.0)
    assert eng.journal.states["surge"]["state"] == "firing"
    # flat signal over a full window resolves
    eng.evaluate(signals={"counter:c": 150.0}, now=122.0)
    assert eng.journal.states["surge"]["state"] == "resolved"


def test_rate_window_restarts_after_replay(tmp_path):
    rules = [{"name": "surge", "kind": "rate", "signal": "counter:c",
              "op": ">", "value": 1.0, "window": 10.0}]
    eng = _engine(tmp_path, rules, sinks=[])
    eng.evaluate(signals={"counter:c": 0.0}, now=100.0)
    eng.evaluate(signals={"counter:c": 200.0}, now=110.5)
    assert eng.journal.states["surge"]["state"] == "firing"
    # the sample ring is derived state, never journaled: a restarted
    # engine needs a fresh covered window before it can re-breach —
    # the conservative side — but the journaled FIRING state survives
    eng2 = _engine(tmp_path, rules, sinks=[])
    assert eng2.journal.states["surge"]["state"] == "firing"
    assert eng2._samples == {}


# ------------------------------------------------- journal durability

def test_journal_replay_identical_digest(tmp_path):
    eng = _engine(tmp_path, [{"name": "a", "signal": "gauge:x",
                              "op": ">", "value": 0.0}], sinks=[])
    eng.evaluate(signals={"gauge:x": 1.0}, now=10.0)
    eng.evaluate(signals={"gauge:x": 0.0}, now=20.0)
    eng.evaluate(signals={"gauge:x": 2.0}, now=30.0)
    replay = AlertJournal(alerts_path(str(tmp_path)))
    assert replay.digest() == eng.journal.digest()
    assert replay.states == eng.journal.states


def test_torn_tail_ignored_then_healed_on_next_append(tmp_path):
    eng = _engine(tmp_path, [{"name": "a", "signal": "gauge:x",
                              "op": ">", "value": 0.0}], sinks=[])
    eng.evaluate(signals={"gauge:x": 1.0}, now=10.0)
    good = eng.journal.digest()
    path = alerts_path(str(tmp_path))
    with open(path, "ab") as f:
        f.write(b'{"ev": "state", "rule": "a", "state": "resol')
    # the torn tail is invisible to replay...
    j2 = AlertJournal(path)
    assert j2.digest() == good
    # ...and the next append through that journal truncates it first
    j2.transition(Rule("a", signal="gauge:x"), "resolved", 0.0,
                  at=20.0)
    j3 = AlertJournal(path)
    assert j3.states["a"]["state"] == "resolved"
    assert j3.digest() == j2.digest()


def test_notify_intent_is_at_most_once_across_replay(tmp_path):
    rules = [{"name": "a", "signal": "gauge:x", "op": ">",
              "value": 0.0}]
    cap = _Capture()
    eng = _engine(tmp_path, rules, sinks=[cap])
    eng.evaluate(signals={"gauge:x": 1.0}, now=10.0)
    assert len(cap.sent) == 1
    # a replayed engine re-fed the same breaching signal: state is
    # already firing at the journaled seq -> nothing new to send
    cap2 = _Capture()
    eng2 = _engine(tmp_path, rules, sinks=[cap2])
    eng2.evaluate(signals={"gauge:x": 1.0}, now=20.0)
    assert not cap2.sent
    assert eng2.journal.digest() == eng.journal.digest()


def test_failed_sink_audited_not_fatal_and_not_retried(tmp_path):
    dead = _Capture(fail=True)
    live = _Capture()
    eng = _engine(tmp_path, [{"name": "a", "signal": "gauge:x",
                              "op": ">", "value": 0.0}],
                  sinks=[dead, live])
    eng.evaluate(signals={"gauge:x": 1.0}, now=10.0)
    # the dead sink never blocks the live one; the failure is audited
    assert len(live.sent) == 1
    assert eng.journal.sends_failed >= 1 and eng.journal.sends_ok == 1
    # audit counters are observability, NOT state: replay digest
    # matches even though notify-result events differ per delivery
    assert AlertJournal(alerts_path(str(tmp_path))).digest() == \
        eng.journal.digest()


def test_kill9_mid_firing_replays_identical_no_duplicate(tmp_path):
    """The acceptance criterion's crash seam, in miniature: SIGKILL a
    process that journaled the firing transition + notify intent, then
    replay — identical digest, and re-evaluation sends nothing new."""
    store = tmp_path / "store"
    notif = tmp_path / "notif.jsonl"
    prog = textwrap.dedent(f"""
        import os, signal
        from jepsen_tpu.telemetry import alerts as A
        eng = A.AlertEngine({str(store)!r}, rules=A.load_rules(
            [{{"name": "a", "signal": "gauge:x", "op": ">",
               "value": 0.0}}]),
            sinks=[A.FileSink({str(notif)!r})])
        eng.evaluate(signals={{"gauge:x": 1.0}}, now=10.0)
        print("FIRED", eng.journal.digest(), flush=True)
        os.kill(os.getpid(), signal.SIGKILL)
    """)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", prog],
                          capture_output=True, text=True, timeout=120,
                          env=env)
    assert proc.returncode == -signal.SIGKILL, proc.stderr
    word, digest = proc.stdout.split()
    assert word == "FIRED"
    replay = AlertJournal(alerts_path(str(store)))
    assert replay.digest() == digest
    assert replay.states["a"]["state"] == "firing"
    with open(notif) as f:
        n0 = sum(1 for ln in f if ln.strip())
    assert n0 == 1
    # the restarted engine re-fed the same breach: zero new deliveries
    eng = AlertEngine(str(store), rules=load_rules(
        [{"name": "a", "signal": "gauge:x", "op": ">", "value": 0.0}]),
        sinks=[alerts_mod.FileSink(str(notif))])
    eng.evaluate(signals={"gauge:x": 1.0}, now=20.0)
    with open(notif) as f:
        assert sum(1 for ln in f if ln.strip()) == n0
    assert eng.journal.digest() == digest


# ----------------------------------------------------------- signals

def test_registry_signals_sum_label_sets(tmp_path):
    from jepsen_tpu.telemetry import metrics

    reg = metrics.Registry()
    reg.gauge("fleet-cells", state="queued").set(4)
    reg.gauge("fleet-cells", state="done").set(6)
    reg.counter("compile-cache-fallthrough", site="a").inc(2)
    reg.counter("compile-cache-fallthrough", site="b").inc(3)
    out = collect_signals(str(tmp_path), registry=reg, now=100.0)
    assert out["gauge:fleet-cells"] == 10.0
    assert out["counter:compile-cache-fallthrough"] == 5.0
    assert out["store:fleet-bytes"] == 0.0


def test_heartbeat_signals_ages_and_max(tmp_path):
    cdir = tmp_path / "campaigns"
    os.makedirs(cdir)
    with open(cdir / "soak.live.json", "w") as f:
        json.dump({"campaign": "soak", "updated": 900.0, "total": 10,
                   "done": 4, "finished": False}, f)
    with open(cdir / "old.live.json", "w") as f:
        json.dump({"campaign": "old", "updated": 100.0, "done": 9,
                   "total": 9, "finished": True}, f)
    from jepsen_tpu.telemetry import metrics

    out = collect_signals(str(tmp_path), registry=metrics.Registry(),
                          now=1000.0)
    assert out["heartbeat:soak:age-s"] == 100.0
    assert out["heartbeat:soak:done"] == 4.0
    assert out["heartbeat:old:finished"] == 1.0
    # finished campaigns never drive max-age (they are DONE, not stale)
    assert out["heartbeat:max-age-s"] == 100.0


def test_autopilot_gate_signals(tmp_path):
    from jepsen_tpu.fleet import AutopilotJournal

    j = AutopilotJournal(str(tmp_path / "ap.jsonl"))
    j.open_gen("g0000", runs=3)
    j.close_gen("g0000", [{"span": "workload", "rc": 2}])
    j.open_gen("g0001", runs=3)
    j.close_gen("g0001", [{"span": "workload", "rc": 1,
                           "key": "k", "status": "regression"}])
    out = {}
    alerts_mod._autopilot_signals(out, j)
    assert out["autopilot:gate-regression"] == 1.0
    assert out["autopilot:gate-rc2-streak"] == 0.0
    assert out["autopilot:quarantined-active"] == 0.0


# --------------------------------------------------- twin-pass parole

SPEC = {"name": "twin", "workloads": ["bank"], "seeds": [0],
        "opts": {"time-limit": 0.2}}


def _quarantined_ap(tmp_path, digest):
    """An autopilot whose journal holds one quarantined key with a
    shrink outcome carrying `digest` (None = shrink had no witness)."""
    from jepsen_tpu.fleet import Autopilot

    ap = Autopilot(SPEC, str(tmp_path / "store"), generations=1,
                   poll_s=0.02)
    key = "bank|nofault|s0"
    ap.journal.open_gen("g0000", runs=1)
    ap.journal.close_gen("g0000", [])
    ap.journal.quarantine(key, gen="g0000", span="workload")
    outcome = {"run": "r0"}
    if digest is not None:
        outcome["digest"] = digest
    ap.journal.shrink(key, gen="g0000", outcome=outcome)
    return ap, key


def _witnessed_run(ap, key, history, tmp_path):
    """Archive `history` as the key's witness run: witness artifacts
    on disk + the index record the autopilot's shrink would append."""
    from jepsen_tpu.minimize import witness as witness_mod

    run_dir = str(tmp_path / "store" / "runs" / "r0")
    digest = witness_mod.history_digest(history)[:16]
    witness_mod.save_witness(run_dir, history, {"target": "any"})
    with ap.coordinator._lock:
        ap.coordinator.idx.append(
            {"run": "r0", "key": key, "dir": "runs/r0",
             "witness": {"digest": digest, "ops": len(history)}})
    return digest


def test_twin_pass_allows_parole_on_valid_witness(tmp_path):
    from jepsen_tpu.workloads import synth

    h = synth.la_history(n_txns=15, n_keys=3, concurrency=3, seed=1)
    ap, key = _quarantined_ap(tmp_path, None)
    try:
        digest = _witnessed_run(ap, key, h, tmp_path)
        ap.journal.shrink(key, gen="g0000",
                          outcome={"digest": digest})
        allowed, twin = ap._witness_twin_check(key)
        assert allowed is True
        assert twin["valid?"] is True and twin["digest"] == digest
    finally:
        ap.close()


def test_twin_fail_denies_parole_on_real_anomaly(tmp_path):
    from jepsen_tpu.workloads import synth

    h = synth.la_history(n_txns=15, n_keys=3, concurrency=3, seed=2)
    assert synth.inject_wr_cycle(h)
    ap, key = _quarantined_ap(tmp_path, None)
    try:
        digest = _witnessed_run(ap, key, h, tmp_path)
        ap.journal.shrink(key, gen="g0000",
                          outcome={"digest": digest})
        allowed, twin = ap._witness_twin_check(key)
        assert allowed is False
        assert twin["valid?"] is False
        # the verdict is cached per digest: a second ask is identical
        assert ap._witness_twin_check(key) == (allowed, twin)
    finally:
        ap.close()


def test_twin_missing_witness_denies_conservatively(tmp_path):
    ap, key = _quarantined_ap(tmp_path, "feedbeefcafe0000")
    try:
        allowed, twin = ap._witness_twin_check(key)
        assert allowed is False
        assert "error" in twin
    finally:
        ap.close()


def test_no_witness_digest_keeps_plain_criterion(tmp_path):
    # perf-only regressions shrink to nothing: no digest in the
    # outcome -> the clean-generations criterion stands alone
    ap, key = _quarantined_ap(tmp_path, None)
    try:
        assert ap._witness_twin_check(key) == (True, None)
    finally:
        ap.close()


def test_parole_event_with_twin_field_is_replay_stable(tmp_path):
    from jepsen_tpu.fleet import AutopilotJournal

    path = str(tmp_path / "ap.jsonl")
    j = AutopilotJournal(path)
    j.open_gen("g0000", runs=1)
    j.close_gen("g0000", [])
    j.quarantine("k", gen="g0000", span="workload")
    j.parole("k", gen="g0000",
             twin={"digest": "abc", "valid?": True})
    with open(path) as f:
        evs = [json.loads(ln) for ln in f if ln.strip()]
    assert any(e.get("ev") == "parole" and e.get("twin")
               for e in evs)
    # replay applies key/gen alone: a journal WITHOUT the twin field
    # reaches the identical digest
    stripped = str(tmp_path / "stripped.jsonl")
    with open(stripped, "w") as f:
        for e in evs:
            e.pop("twin", None)
            f.write(json.dumps(e, sort_keys=True) + "\n")
    assert AutopilotJournal(stripped).digest() == \
        AutopilotJournal(path).digest()


# ------------------------------------------------------ status + web

def test_status_doc_shape(tmp_path):
    eng = _engine(tmp_path, [{"name": "a", "signal": "gauge:x",
                              "op": ">", "value": 0.0, "for": 60.0}],
                  sinks=[])
    eng.evaluate(signals={"gauge:x": 1.0}, now=10.0)
    doc = eng.status_doc()
    assert doc["pending"] == ["a"] and doc["firing"] == []
    assert doc["active"][0]["rule"] == "a"
    assert doc["rules"] == 1 and "digest" in doc


def test_exposition_renders_only_active_alerts(tmp_path):
    from jepsen_tpu.telemetry import metrics, prometheus as prom

    eng = _engine(tmp_path, [{"name": "a", "signal": "gauge:x",
                              "op": ">", "value": 0.0}], sinks=[])
    eng.evaluate(signals={"gauge:x": 1.0}, now=10.0)
    expo = prom.exposition(base=str(tmp_path),
                           registry=metrics.Registry(), now=11.0)
    assert ('ALERTS{alertname="a",severity="warn",state="firing"} 1'
            in expo)
    eng.evaluate(signals={"gauge:x": 0.0}, now=12.0)
    expo = prom.exposition(base=str(tmp_path),
                           registry=metrics.Registry(), now=13.0)
    assert "ALERTS{" not in expo
