"""Batched/sharded checking paths (parallel/batch.py, device_core exact).

Differential style per SURVEY.md §4: sharded and rebatched results must
equal the plain single-device verdicts.
"""

import os

import numpy as np
import pytest

from jepsen_tpu.checkers.elle.device_core import (
    core_check,
    core_check_exact,
)
from jepsen_tpu.checkers.elle.device_infer import pad_packed
from jepsen_tpu.history.soa import pack_txns
from jepsen_tpu.parallel.batch import check_batch, make_mesh
from jepsen_tpu.workloads import synth


def test_check_batch_unsharded():
    ps = [synth.packed_la_history(n_txns=48, n_keys=4, seed=s)
          for s in range(4)]
    results = check_batch(ps)
    assert len(results) == 4
    assert all(r["valid?"] is True for r in results)


def test_check_batch_sharded_non_divisible():
    # 10 histories on an 8-device mesh: batch must be padded to 16 and
    # the padding rows dropped
    mesh = make_mesh(8)
    ps = [synth.packed_la_history(n_txns=48, n_keys=4, seed=s)
          for s in range(10)]
    results = check_batch(ps, mesh=mesh)
    assert len(results) == 10
    assert all(r["valid?"] is True for r in results)


def _cyclic_packed(seed=5, n_inject=8):
    h = synth.la_history(n_txns=120, n_keys=5, concurrency=6,
                         multi_append_prob=0.2, seed=seed)
    for _ in range(n_inject):
        synth.inject_wr_cycle(h)
        synth.inject_rw_cycle(h)
    return pack_txns(h, "list-append")


def test_core_check_exact_rebatches_overflow():
    p = _cyclic_packed()
    hp = pad_packed(p)
    _, over_small = core_check(hp, p.n_keys, max_k=2)
    assert int(np.asarray(over_small)) > 0, "fixture must overflow max_k=2"

    bits, over = core_check_exact(hp, p.n_keys, max_k=2, max_rounds=8)
    bits_ref, over_ref = core_check(hp, p.n_keys)
    assert int(np.asarray(over)) == int(np.asarray(over_ref)) == 0
    assert np.array_equal(np.asarray(bits), np.asarray(bits_ref))
    assert int(np.asarray(bits)[-1]) == 1  # converged


def test_check_sharded_differential():
    # one history op-sharded over the 8-device mesh must give bitwise the
    # same verdict as the single-device core check (config-4 shape)
    import jax

    from jepsen_tpu.parallel.op_shard import _core_check_sharded, \
        check_sharded

    mesh = make_mesh(8)
    cases = [synth.packed_la_history(n_txns=96, n_keys=6, seed=99)]
    for seed in (3, 5):
        h = synth.la_history(n_txns=100, n_keys=5, concurrency=6,
                             multi_append_prob=0.2, seed=seed)
        if seed == 3:
            synth.inject_rw_cycle(h)
        else:
            synth.inject_wr_cycle(h)
            synth.inject_g1a(h)
        cases.append(pack_txns(h, "list-append"))

    for p in cases:
        hp = pad_packed(p)
        bits_ref, over_ref = core_check(hp, p.n_keys)
        bits_sh, over_sh = _core_check_sharded(hp, p.n_keys, mesh, "dp")
        assert np.array_equal(np.asarray(bits_sh), np.asarray(bits_ref))
        assert int(np.asarray(over_sh)) == int(np.asarray(over_ref))


def test_check_sharded_overflow_rebatch():
    from jepsen_tpu.parallel.op_shard import check_sharded

    mesh = make_mesh(8)
    p = _cyclic_packed()
    r = check_sharded(p, mesh=mesh, max_k=8)  # forces growth, 8 % 8 == 0
    assert r["valid?"] is False
    assert r["exact"] is True


def test_check_sharded_non_pow2_mesh():
    # 6 devices don't divide max_k=128: the budget must round up, not die
    from jepsen_tpu.parallel.op_shard import check_sharded

    mesh = make_mesh(6)
    p = synth.packed_la_history(n_txns=48, n_keys=4, seed=2)
    r = check_sharded(p, mesh=mesh)
    assert r["valid?"] is True


def test_check_batch_recovers_overflowed_history():
    # a batch mixing valid histories with one that overflows the default
    # budget path at small max_k must still get a definitive verdict
    ps = [synth.packed_la_history(n_txns=48, n_keys=4, seed=s)
          for s in range(3)] + [_cyclic_packed()]
    results = check_batch(ps)
    assert [r["valid?"] for r in results[:3]] == [True, True, True]
    assert results[3]["valid?"] is False  # injected cycles, definitive
    assert results[3]["exact"] is True


def test_check_sharded_reports_inference_sharding():
    from jepsen_tpu.parallel.op_shard import check_sharded

    # pow2 mesh divides pow2-padded arrays -> inference sharded
    p = synth.packed_la_history(n_txns=48, n_keys=4, seed=2)
    r8 = check_sharded(p, mesh=make_mesh(8))
    assert r8["inference-sharded"] is True
    # 6-device mesh never divides pow2 capacities -> replicated, and the
    # result dict must SAY so (round-2 verdict: docstring-only was not ok)
    r6 = check_sharded(p, mesh=make_mesh(6))
    assert r6["inference-sharded"] is False
    assert r6["valid?"] is True and r8["valid?"] is True


@pytest.mark.skipif(not os.environ.get("JT_SCALE_TESTS"),
                    reason="set JT_SCALE_TESTS=1: ~10 min, >=1M-txn "
                           "sharded differential (run for PROFILE.md)")
def test_check_sharded_differential_1m():
    # VERDICT round 2: the config-4 sharding was only ever validated at
    # <=120 txns; this exercises the K-axis sharded sweep + GSPMD
    # inference at 1M txns on the 8-CPU mesh and pins bitwise equality
    # against the single-device core check
    import time

    import jax

    from jepsen_tpu.parallel.op_shard import _core_check_sharded

    mesh = make_mesh(8)
    p = synth.packed_la_history(n_txns=1_000_000, n_keys=125_000,
                                mops_per_txn=4, read_frac=0.25, seed=7)
    hp = pad_packed(p)
    t0 = time.perf_counter()
    bits_ref, over_ref = core_check(hp, p.n_keys)
    jax.block_until_ready(bits_ref)
    t_ref = time.perf_counter() - t0
    t0 = time.perf_counter()
    bits_sh, over_sh = _core_check_sharded(hp, p.n_keys, mesh, "dp")
    jax.block_until_ready(bits_sh)
    t_sh = time.perf_counter() - t0
    assert np.array_equal(np.asarray(bits_sh), np.asarray(bits_ref))
    assert int(np.asarray(over_sh)) == int(np.asarray(over_ref)) == 0
    assert int(np.asarray(bits_ref)[-1]) == 1
    print(f"\n1M sharded differential: ref {t_ref:.1f}s, "
          f"sharded {t_sh:.1f}s (incl. compile)")


def test_check_batch_checkpointed_resume(tmp_path):
    from jepsen_tpu.parallel.batch import check_batch_checkpointed

    ps = [synth.packed_la_history(n_txns=48, n_keys=4, seed=s)
          for s in range(7)]
    ck = str(tmp_path / "ck.jsonl")
    want = check_batch(ps)

    # first run, small groups: several checkpoint appends
    got = check_batch_checkpointed(ps, ck, group_size=3)
    assert got == want
    n_lines = sum(1 for line in open(ck) if line.strip())
    assert n_lines == 7

    # resume: nothing recomputed, same results (file untouched)
    again = check_batch_checkpointed(ps, ck, group_size=3)
    assert again == want
    assert sum(1 for line in open(ck) if line.strip()) == 7

    # partial checkpoint: drop the last 3 lines, resume completes them
    lines = [line for line in open(ck) if line.strip()]
    with open(ck, "w") as f:
        f.writelines(lines[:4])
    resumed = check_batch_checkpointed(ps, ck, group_size=3)
    assert resumed == want
    assert sum(1 for line in open(ck) if line.strip()) == 7


def test_check_batch_checkpointed_rejects_foreign_batch(tmp_path):
    from jepsen_tpu.parallel.batch import check_batch_checkpointed

    ps = [synth.packed_la_history(n_txns=48, n_keys=4, seed=s)
          for s in range(3)]
    ck = str(tmp_path / "ck.jsonl")
    check_batch_checkpointed(ps, ck)
    other = [synth.packed_la_history(n_txns=48, n_keys=4, seed=s + 50)
             for s in range(3)]
    with pytest.raises(ValueError, match="different batch"):
        check_batch_checkpointed(other, ck)


def test_check_batch_checkpointed_tolerates_torn_line(tmp_path):
    from jepsen_tpu.parallel.batch import check_batch_checkpointed

    ps = [synth.packed_la_history(n_txns=48, n_keys=4, seed=s)
          for s in range(4)]
    ck = str(tmp_path / "ck.jsonl")
    want = check_batch_checkpointed(ps, ck, group_size=2)

    # simulate a crash mid-append: truncate the last record mid-way
    data = open(ck, "rb").read()
    open(ck, "wb").write(data[:-17])
    got = check_batch_checkpointed(ps, ck, group_size=2)
    assert got == want
    # the file healed: every line parses and all 4 records are present
    import json

    recs = [json.loads(line) for line in open(ck) if line.strip()]
    assert sorted(r["i"] for r in recs) == [0, 1, 2, 3]


def test_check_batch_hybrid_differential():
    """2D (dcn x k) hybrid checking must be bitwise-identical to plain
    check_batch — including a seeded-anomaly history and a batch that
    doesn't divide the dcn axis."""
    from jepsen_tpu.parallel.hybrid import check_batch_hybrid, \
        make_hybrid_mesh

    ps = [synth.packed_la_history(n_txns=48, n_keys=4, seed=s)
          for s in range(5)]
    bad = synth.la_history(n_txns=48, n_keys=4, concurrency=4, seed=13)
    assert synth.inject_wr_cycle(bad)
    ps.append(pack_txns(bad, "list-append"))

    want = check_batch(ps)
    mesh = make_hybrid_mesh(2, 4)
    got = check_batch_hybrid(ps, mesh)  # 6 histories over 2 dcn rows
    assert got == want
    assert got[-1]["valid?"] is False and got[-1]["cycles"]["G1c"]


def test_check_batch_hybrid_4x2():
    from jepsen_tpu.parallel.hybrid import check_batch_hybrid, \
        make_hybrid_mesh

    ps = [synth.packed_la_history(n_txns=40, n_keys=4, seed=s + 20)
          for s in range(3)]
    want = check_batch(ps)
    got = check_batch_hybrid(ps, make_hybrid_mesh(4, 2))  # pad 3 -> 4 rows
    assert got == want


def test_check_batch_hybrid_overflow_fallback():
    # a history that overflows tiny max_k must reach the exact-rerun
    # fallback (the path where a read-only numpy view once crashed) and
    # still produce a definitive verdict
    from jepsen_tpu.parallel.hybrid import check_batch_hybrid, \
        make_hybrid_mesh

    ps = [synth.packed_la_history(n_txns=48, n_keys=4, seed=1),
          _cyclic_packed()]
    got = check_batch_hybrid(ps, make_hybrid_mesh(2, 2), max_k=4)
    assert got[0]["valid?"] is True
    assert got[1]["valid?"] is False and got[1]["exact"] is True


@pytest.mark.skipif(not os.environ.get("JT_SCALE_TESTS"),
                    reason="set JT_SCALE_TESTS=1: ~15 min, 4 x 200k-txn "
                           "hybrid (dcn x k) differential")
def test_check_batch_hybrid_500k():
    # config-5 rehearsal at scale: 4 x 200k-txn histories over a (2, 4)
    # mesh — batch rows x sweep windows — bitwise-equal to the unsharded
    # batch path.  200k, not 1M: the virtual mesh serializes every
    # device onto the host cores, and XLA:CPU's collective rendezvous
    # hard-aborts (CHECK-fail) when participants arrive > 40 s apart —
    # on a single-core host the per-device inference at 500k shapes
    # already exceeds that (measured round 5; 500k passed on the
    # earlier multi-core box).  On real chips devices run in parallel
    # and the constraint vanishes; the per-device footprint is ~1 GB
    # at 1M.
    from jepsen_tpu.parallel.hybrid import check_batch_hybrid, \
        make_hybrid_mesh

    ps = [synth.packed_la_history(n_txns=200_000, n_keys=25_000,
                                  mops_per_txn=4, read_frac=0.25, seed=s)
          for s in range(4)]
    got = check_batch_hybrid(ps, make_hybrid_mesh(2, 4))
    want = check_batch(ps)
    assert got == want
    assert all(r["valid?"] is True and r["exact"] for r in got)


def test_sharded_default_differential_shard_counts(monkeypatch):
    """ISSUE 12 acceptance pin: the sharded-DEFAULT path
    (`core_check_auto` under a forced JEPSEN_SHARDS) at shard counts
    1/2/4 is bitwise-equal to the single-device core check, and the
    full `list_append.check` pipeline agrees with the HOST ORACLE
    verdict-and-anomaly-set on the seeded anomaly corpora."""
    from jepsen_tpu.checkers.elle import list_append, oracle
    from jepsen_tpu.checkers.elle.device_core import core_check, \
        core_check_auto

    cases = [synth.packed_la_history(n_txns=96, n_keys=6, seed=12)]
    hs = []
    for seed in (4, 6):
        h = synth.la_history(n_txns=110, n_keys=5, concurrency=6,
                             multi_append_prob=0.2, seed=seed)
        if seed == 4:
            synth.inject_wr_cycle(h)
            synth.inject_g1a(h)
        else:
            synth.inject_rw_cycle(h)
        hs.append(h)
        cases.append(pack_txns(h, "list-append"))

    for n in ("1", "2", "4"):
        monkeypatch.setenv("JEPSEN_SHARDS", n)
        for p in cases:
            hp = pad_packed(p)
            bits_ref, over_ref = core_check(hp, p.n_keys)
            bits_sh, over_sh = core_check_auto(hp, p.n_keys)
            assert np.array_equal(np.asarray(bits_sh),
                                  np.asarray(bits_ref)), n
            assert int(np.asarray(over_sh)) == int(np.asarray(over_ref))
        for h in hs:
            dev = list_append.check(h, ("strict-serializable",))
            ref = oracle.check(h, ("strict-serializable",))
            assert dev["valid?"] == ref["valid?"], n
            assert sorted(dev["anomaly-types"]) == \
                sorted(ref["anomaly-types"]), n


def test_default_mesh_gates(monkeypatch):
    """Mesh resolution: forced JEPSEN_SHARDS activates sharding on any
    backend; unforced CPU stays single-device (virtual host devices on
    shared cores cannot win, and big-shape GSPMD compiles are
    pathological on XLA:CPU); sub-threshold histories stay
    single-device; slot slices carve the device set."""
    from jepsen_tpu.parallel import slots

    monkeypatch.delenv("JEPSEN_SHARDS", raising=False)
    # unforced on the cpu backend: no default sharding even when large
    assert slots.default_mesh(1 << 20) is None
    monkeypatch.setenv("JEPSEN_SHARDS", "4")
    m = slots.default_mesh(1 << 20)
    assert m is not None and m.devices.size == 4
    assert slots.default_mesh(None) is not None  # forced skips the gate
    monkeypatch.setenv("JEPSEN_SHARDS", "1")
    assert slots.default_mesh(1 << 20) is None
    monkeypatch.delenv("JEPSEN_SHARDS", raising=False)
    # slot slices: 8 devices / 4 slots -> 2 devices per slot
    devs = slots.slot_devices(1, 4)
    assert len(devs) == 2
    try:
        slots.set_active_slot(1, 4)
        assert len(slots._visible_devices()) == 2
    finally:
        slots.set_active_slot(None)
